"""Tune tests (reference test-strategy analogue: python/ray/tune/tests —
trial runner, searchers, schedulers on toy objective functions)."""
import pytest

from ray_tpu import tune
from ray_tpu.train.config import RunConfig
from ray_tpu.tune import (ASHAScheduler, PopulationBasedTraining, TuneConfig,
                          Tuner)


def _objective(config):
    # quadratic bowl: best at x = 3
    for i in range(5):
        loss = (config["x"] - 3.0) ** 2 + 0.1 * i
        tune.report({"loss": loss})


def test_grid_search(tmp_path):
    tuner = Tuner(
        _objective,
        param_space={"x": tune.grid_search([0.0, 3.0, 5.0])},
        tune_config=TuneConfig(metric="loss", mode="min"),
        run_config=RunConfig(name="grid", storage_path=str(tmp_path)))
    grid = tuner.fit()
    assert len(grid) == 3
    best = grid.get_best_result()
    assert abs(best.metrics["loss"] - 0.4) < 1e-6  # x=3 after 5 steps


def test_random_search_num_samples(tmp_path):
    tuner = Tuner(
        _objective,
        param_space={"x": tune.uniform(-5, 5)},
        tune_config=TuneConfig(num_samples=6, seed=0),
        run_config=RunConfig(name="rand", storage_path=str(tmp_path)))
    grid = tuner.fit()
    assert len(grid) == 6
    xs = {t.config["x"] for t in grid.trials}
    assert len(xs) == 6  # all distinct draws


def test_class_trainable(tmp_path):
    class Quad(tune.Trainable):
        def setup(self, config):
            self.x = config["x"]
            self.val = 10.0

        def step(self):
            self.val *= 0.5
            return {"loss": self.val, "done": self.val < 1.0}

        def save_checkpoint(self):
            return {"val": self.val}

        def load_checkpoint(self, ck):
            self.val = ck["val"]

    tuner = Tuner(Quad, param_space={"x": 1.0},
                  run_config=RunConfig(name="cls", storage_path=str(tmp_path)))
    grid = tuner.fit()
    assert grid[0].metrics["loss"] < 1.0
    assert grid[0].metrics["training_iteration"] == 4


def test_asha_stops_bad_trials(tmp_path):
    def slow_objective(config):
        for i in range(20):
            tune.report({"loss": config["x"] + 100.0 / (i + 1)})

    sched = ASHAScheduler(metric="loss", mode="min", max_t=20,
                          grace_period=2, reduction_factor=2)
    tuner = Tuner(
        slow_objective,
        param_space={"x": tune.grid_search([1.0, 2.0, 3.0, 4.0])},
        tune_config=TuneConfig(scheduler=sched),
        run_config=RunConfig(name="asha", storage_path=str(tmp_path)))
    grid = tuner.fit()
    iters = sorted(t.iterations for t in grid.trials)
    # at least one trial cut before max_t, the best one ran to the end
    assert iters[0] < 20
    assert iters[-1] == 20


def test_pbt_exploits(tmp_path):
    class Walker(tune.Trainable):
        def setup(self, config):
            self.lr = config["lr"]
            self.score = 0.0

        def step(self):
            # good lr climbs fast
            self.score += self.lr
            return {"score": self.score}

        def save_checkpoint(self):
            return {"score": self.score, "lr": self.lr}

        def load_checkpoint(self, ck):
            self.score = ck["score"]

        def reset_config(self, cfg):
            self.lr = cfg["lr"]
            return True

    sched = PopulationBasedTraining(
        metric="score", mode="max", perturbation_interval=2,
        hyperparam_mutations={"lr": tune.uniform(0.1, 1.0)}, seed=1)
    tuner = Tuner(
        Walker,
        param_space={"lr": tune.grid_search([0.01, 1.0])},
        tune_config=TuneConfig(metric="score", mode="max", scheduler=sched),
        run_config=RunConfig(name="pbt", storage_path=str(tmp_path)))

    # drive manually for a bounded number of steps via ASHA-style max:
    # simpler — wrap objective count inside trainable: run 8 iterations
    class Walker8(Walker):
        def step(self):
            r = super().step()
            r["done"] = self._iteration >= 7
            return r

    tuner.trainable_cls = Walker8
    grid = tuner.fit()
    scores = [t.last_result["score"] for t in grid.trials]
    # the weak trial was lifted by exploiting the strong one's weights
    assert min(scores) > 0.08 * 8


def test_function_trainable_checkpoint_restore(tmp_path):
    def fn(config):
        ck = tune.get_checkpoint()
        start = ck["i"] if ck else 0
        for i in range(start, 3):
            tune.report({"i": i}, checkpoint={"i": i + 1})

    cls = tune.wrap_function(fn)
    t = cls({})
    r1 = t.train()
    assert r1["i"] == 0
    saved = t.save()
    t2 = cls({})
    t2.restore(saved)
    out = [t2.train()["i"] for _ in range(2)]
    assert out == [1, 2]


def test_actor_mode(tmp_path):
    import ray_tpu
    ray_tpu.init(num_cpus=2, num_tpus=0)
    try:
        tuner = Tuner(
            _objective,
            param_space={"x": tune.grid_search([1.0, 3.0])},
            tune_config=TuneConfig(use_actors=True,
                                   max_concurrent_trials=2),
            run_config=RunConfig(name="act", storage_path=str(tmp_path)))
        grid = tuner.fit()
        assert len(grid) == 2
        assert not grid.errors
    finally:
        ray_tpu.shutdown()


def test_concurrency_limiter_runs_all(tmp_path):
    from ray_tpu.tune import BasicVariantGenerator, ConcurrencyLimiter
    limiter = ConcurrencyLimiter(
        BasicVariantGenerator({"x": tune.uniform(0, 1)}, num_samples=5,
                              seed=0),
        max_concurrent=2)
    tuner = Tuner(
        _objective,
        tune_config=TuneConfig(search_alg=limiter),
        run_config=RunConfig(name="lim", storage_path=str(tmp_path)))
    grid = tuner.fit()
    assert len(grid) == 5  # all samples ran despite the cap
    assert all(t.status == "TERMINATED" for t in grid.trials)


# -- new schedulers --------------------------------------------------------

def test_median_stopping_rule():
    from ray_tpu import tune
    from ray_tpu.tune.schedulers import MedianStoppingRule

    def train_fn(config):
        for i in range(10):
            # bad configs plateau high, good ones descend
            tune.report({"loss": config["base"] - i * config["slope"]})

    res = tune.Tuner(
        train_fn,
        param_space={"base": tune.choice([10.0]),
                     "slope": tune.grid_search([0.0, 0.0, 0.0, 1.0])},
        tune_config=tune.TuneConfig(
            metric="loss", mode="min",
            scheduler=MedianStoppingRule(metric="loss", mode="min",
                                         grace_period=2,
                                         min_samples_required=2),
            max_concurrent_trials=4),
    ).fit()
    best = res.get_best_result()
    assert best.metrics["loss"] <= 1.0   # the improving trial survived


def test_hyperband_brackets():
    from ray_tpu import tune
    from ray_tpu.tune.schedulers import HyperBandScheduler

    def train_fn(config):
        for i in range(9):
            tune.report({"loss": config["x"] / (i + 1)})

    res = tune.Tuner(
        train_fn,
        param_space={"x": tune.grid_search([1.0, 2.0, 3.0, 4.0, 5.0, 6.0])},
        tune_config=tune.TuneConfig(
            metric="loss", mode="min",
            scheduler=HyperBandScheduler(metric="loss", mode="min",
                                         max_t=9, reduction_factor=3,
                                         num_brackets=2),
            max_concurrent_trials=6),
    ).fit()
    assert len(res) == 6
    assert res.get_best_result().metrics["loss"] <= 1.0


# -- loggers / callbacks ---------------------------------------------------

def test_logger_callbacks(tmp_path):
    import json as _json
    from ray_tpu import tune
    from ray_tpu.train.config import RunConfig

    def train_fn(config):
        for i in range(3):
            tune.report({"loss": float(i), "lr": config["lr"]})

    cbs = [tune.CSVLoggerCallback(), tune.JSONLoggerCallback()]
    res = tune.Tuner(
        train_fn, param_space={"lr": tune.grid_search([0.1, 0.2])},
        tune_config=tune.TuneConfig(metric="loss", mode="min"),
        run_config=RunConfig(name="cbrun", storage_path=str(tmp_path),
                             callbacks=cbs),
    ).fit()
    run_dir = str(tmp_path / "cbrun")
    import os
    tdirs = [d for d in os.listdir(run_dir) if d.startswith("trial_")]
    assert len(tdirs) == 2
    for td in tdirs:
        prog = os.path.join(run_dir, td, "progress.csv")
        with open(prog) as f:
            lines = f.read().strip().splitlines()
        # header + 3 reports (+ optional final done-marker result)
        assert len(lines) in (4, 5)
        rj = os.path.join(run_dir, td, "result.json")
        rows = [_json.loads(l) for l in open(rj)]
        assert rows[-1]["loss"] == 2.0
        params = _json.load(open(os.path.join(run_dir, td, "params.json")))
        assert "lr" in params


def test_stop_criteria():
    from ray_tpu import tune
    from ray_tpu.train.config import RunConfig

    def train_fn(config):
        for i in range(100):
            tune.report({"score": float(i)})

    res = tune.Tuner(
        train_fn, param_space={},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(name="stoprun", stop={"score": 5.0}),
    ).fit()
    assert res.get_best_result().metrics["score"] == 5.0


# -- experiment checkpoint / restore ---------------------------------------

def test_experiment_state_and_restore(tmp_path):
    from ray_tpu import tune
    from ray_tpu.train.config import RunConfig

    def train_fn(config):
        for i in range(4):
            tune.report({"loss": config["x"] - i})

    tuner = tune.Tuner(
        train_fn, param_space={"x": tune.grid_search([5.0, 7.0])},
        tune_config=tune.TuneConfig(metric="loss", mode="min"),
        run_config=RunConfig(name="resumerun", storage_path=str(tmp_path)))
    res = tuner.fit()
    assert len(res) == 2
    run_dir = str(tmp_path / "resumerun")

    restored = tune.Tuner.restore(run_dir, train_fn)
    res2 = restored.fit()   # everything terminated: instant, results kept
    assert len(res2) == 2
    assert res2.get_best_result().metrics["loss"] == 2.0


def test_restore_continues_unsuggested_configs(tmp_path):
    """An interrupted sweep must finish configs never suggested before
    the interruption (the searcher state rides the experiment
    checkpoint)."""
    import os
    from ray_tpu import tune
    from ray_tpu.train.config import RunConfig

    def train_fn(config):
        if config["x"] == 2 and not os.environ.get("TUNE_RESUMED_T"):
            raise RuntimeError("crash")
        tune.report({"loss": float(config["x"]), "done": True})

    tuner = tune.Tuner(
        train_fn, param_space={"x": tune.grid_search([1, 2, 3, 4])},
        tune_config=tune.TuneConfig(metric="loss", mode="min",
                                    max_concurrent_trials=1),
        run_config=RunConfig(name="cont", storage_path=str(tmp_path)))
    res = tuner.fit()   # x=2 errors; all 4 suggested (concurrency 1)
    assert len(res) == 4

    os.environ["TUNE_RESUMED_T"] = "1"
    try:
        restored = tune.Tuner.restore(str(tmp_path / "cont"), train_fn)
        # restored metric/mode must survive
        assert restored.tune_config.mode == "min"
        res2 = restored.fit()
        assert len(res2) == 4
        assert all(t.status == "TERMINATED" for t in res2.trials)
    finally:
        del os.environ["TUNE_RESUMED_T"]


def test_median_stopping_aligns_iterations():
    """A young trial must be compared against other trials' averages
    truncated to the SAME training step, not their full histories
    (advisor finding r1: younger trials were stopped merely for being
    younger)."""
    from ray_tpu.tune.schedulers import MedianStoppingRule, CONTINUE

    class T:
        def __init__(self, tid):
            self.trial_id = tid

    rule = MedianStoppingRule(metric="loss", mode="min", grace_period=1,
                              min_samples_required=2)
    # two veterans descend from 10.0 to 1.0 over 10 iterations
    for tid in ("a", "b"):
        for i in range(10):
            rule.on_result(T(tid), {"loss": 10.0 - i, "training_iteration": i})
    # a young trial at iteration 1 with the SAME trajectory must survive:
    # at iteration<=1 the veterans averaged (10+9)/2 = 9.5, and the young
    # trial's own average is 9.5 — not worse than the median
    decision = rule.on_result(T("young"), {"loss": 10.0,
                                           "training_iteration": 0})
    assert decision == CONTINUE
    decision = rule.on_result(T("young"), {"loss": 9.0,
                                           "training_iteration": 1})
    assert decision == CONTINUE


def test_csv_logger_appends_after_restore(tmp_path):
    """CSVLoggerCallback must append to an existing progress.csv (restored
    experiment) instead of truncating logged history (advisor finding r1)."""
    import csv as _csv
    from ray_tpu.tune.callback import CSVLoggerCallback

    class T:
        trial_id = "t1"
        config = {"x": 1}

    cb = CSVLoggerCallback()
    cb.setup(str(tmp_path))
    cb.on_trial_result(T(), {"loss": 1.0, "training_iteration": 1})
    cb.on_trial_result(T(), {"loss": 0.5, "training_iteration": 2})

    cb2 = CSVLoggerCallback()   # fresh process after restore
    cb2.setup(str(tmp_path), restored=True)
    cb2.on_trial_result(T(), {"loss": 0.25, "training_iteration": 3})

    with open(tmp_path / "t1" / "progress.csv", newline="") as f:
        rows = list(_csv.DictReader(f))
    assert len(rows) == 3, rows
    assert [float(r["loss"]) for r in rows] == [1.0, 0.5, 0.25]


def test_restore_without_searcher_state_runs_remaining(tmp_path):
    """If the pickled searcher failed to round-trip, restore must still
    run the not-yet-run configs instead of reporting success with a
    truncated sweep (advisor finding r1)."""
    import pickle
    from ray_tpu import tune
    from ray_tpu.train.config import RunConfig

    def train_fn(config):
        tune.report({"loss": float(config["x"]), "done": True})

    tuner = tune.Tuner(
        train_fn, param_space={"x": tune.grid_search([1, 2, 3, 4])},
        run_config=RunConfig(name="nosrch", storage_path=str(tmp_path)))
    assert len(tuner.fit()) == 4

    run_dir = str(tmp_path / "nosrch")
    sp = tuner._experiment_state_path(run_dir)
    with open(sp, "rb") as f:
        payload = pickle.load(f)
    payload["trials"] = payload["trials"][:2]   # interrupted after 2
    payload["searcher"] = None                  # searcher didn't pickle
    with open(sp, "wb") as f:
        pickle.dump(payload, f)

    res = tune.Tuner.restore(run_dir, train_fn).fit()
    assert len(res) == 4
    xs = sorted(t.config["x"] for t in res.trials)
    assert xs == [1, 2, 3, 4]


def test_fresh_rerun_truncates_stale_csv(tmp_path):
    """A brand-new (non-restored) run into a reused directory must
    truncate the previous run's progress.csv, not interleave with it."""
    import csv as _csv
    from ray_tpu.tune.callback import CSVLoggerCallback

    class T:
        trial_id = "t1"
        config = {"x": 1}

    cb = CSVLoggerCallback()
    cb.setup(str(tmp_path))
    cb.on_trial_result(T(), {"loss": 1.0, "training_iteration": 1})

    cb2 = CSVLoggerCallback()
    cb2.setup(str(tmp_path))   # restored NOT set: fresh run, same dir
    cb2.on_trial_result(T(), {"loss": 9.0, "training_iteration": 1})

    with open(tmp_path / "t1" / "progress.csv", newline="") as f:
        rows = list(_csv.DictReader(f))
    assert len(rows) == 1 and float(rows[0]["loss"]) == 9.0


def test_restore_without_searcher_random_search(tmp_path):
    """Count-based skip: even with seedless random search (configs can't
    be re-matched by equality), a restore without searcher state runs
    exactly the REMAINING sample budget, not restored+num_samples."""
    import pickle
    from ray_tpu import tune
    from ray_tpu.train.config import RunConfig

    def train_fn(config):
        tune.report({"loss": float(config["x"]), "done": True})

    tuner = tune.Tuner(
        train_fn, param_space={"x": tune.uniform(0.0, 1.0)},
        tune_config=tune.TuneConfig(num_samples=4),
        run_config=RunConfig(name="rnd", storage_path=str(tmp_path)))
    assert len(tuner.fit()) == 4

    run_dir = str(tmp_path / "rnd")
    sp = tuner._experiment_state_path(run_dir)
    with open(sp, "rb") as f:
        payload = pickle.load(f)
    payload["trials"] = payload["trials"][:2]
    payload["searcher"] = None
    with open(sp, "wb") as f:
        pickle.dump(payload, f)

    res = tune.Tuner.restore(run_dir, train_fn).fit()
    assert len(res) == 4, len(res)


# -- model-based searchers -------------------------------------------------

def _eval_searcher(searcher, objective, n):
    """Drive a searcher directly on a deterministic objective."""
    best = float("inf")
    for i in range(n):
        cfg = searcher.suggest(f"t{i:03d}")
        if cfg is None or cfg == "PENDING":
            break
        loss = objective(cfg)
        best = min(best, loss)
        searcher.on_trial_complete(f"t{i:03d}", {"loss": loss})
    return best


def _branin_ish(cfg):
    # deterministic 2d bowl with a mild non-convexity
    import math
    x, y = cfg["x"], cfg["y"]
    return ((x - 0.3) ** 2 + (y + 0.2) ** 2
            + 0.1 * math.sin(6 * x) * math.sin(6 * y) + 0.11)


def test_tpe_beats_random_search():
    from ray_tpu import tune
    from ray_tpu.tune.search import BasicVariantGenerator
    from ray_tpu.tune.suggest import TPESearcher

    space = {"x": tune.uniform(-1, 1), "y": tune.uniform(-1, 1)}
    n = 40
    tpe_best = min(
        _eval_searcher(TPESearcher(space, num_samples=n, n_startup=8,
                                   seed=s), _branin_ish, n)
        for s in (0, 1, 2))
    rnd_best = min(
        _eval_searcher(BasicVariantGenerator(space, num_samples=n, seed=s),
                       _branin_ish, n)
        for s in (0, 1, 2))
    # TPE must home in on the optimum at least as well as random search
    assert tpe_best <= rnd_best + 1e-9, (tpe_best, rnd_best)
    assert tpe_best < 0.05, tpe_best   # near the global optimum (~0.013)


def test_gp_ei_converges():
    from ray_tpu import tune
    from ray_tpu.tune.suggest import GPSearcher

    space = {"x": tune.uniform(-1, 1), "y": tune.uniform(-1, 1)}
    best = _eval_searcher(GPSearcher(space, num_samples=35, n_startup=8,
                                     seed=0), _branin_ish, 35)
    assert best < 0.08, best


def test_tpe_categorical_and_loguniform():
    from ray_tpu import tune
    from ray_tpu.tune.suggest import TPESearcher

    def objective(cfg):
        import math
        penalty = 0.0 if cfg["act"] == "gelu" else 1.0
        return abs(math.log10(cfg["lr"]) + 3.0) + penalty  # best lr=1e-3

    space = {"lr": tune.loguniform(1e-5, 1e-1),
             "act": tune.choice(["relu", "tanh", "gelu"])}
    s = TPESearcher(space, num_samples=50, n_startup=10, seed=0)
    best = _eval_searcher(s, objective, 50)
    assert best < 0.5, best   # found gelu AND lr within half a decade


def test_tpe_through_tuner(tmp_path):
    from ray_tpu import tune
    from ray_tpu.train.config import RunConfig
    from ray_tpu.tune.suggest import TPESearcher

    def train_fn(config):
        tune.report({"loss": _branin_ish(config), "done": True})

    space = {"x": tune.uniform(-1, 1), "y": tune.uniform(-1, 1)}
    res = tune.Tuner(
        train_fn,
        tune_config=tune.TuneConfig(
            metric="loss", mode="min",
            search_alg=TPESearcher(space, num_samples=25, n_startup=6,
                                   seed=0)),
        run_config=RunConfig(name="tpe", storage_path=str(tmp_path))).fit()
    assert len(res) == 25
    assert res.get_best_result().metrics["loss"] < 0.2


def test_bohb_with_hyperband(tmp_path):
    from ray_tpu import tune
    from ray_tpu.train.config import RunConfig
    from ray_tpu.tune.schedulers import HyperBandScheduler
    from ray_tpu.tune.suggest import TuneBOHB

    def train_fn(config):
        # good configs descend fast; budget-aware model sees partial runs
        for i in range(9):
            tune.report({"loss": _branin_ish(config) + 1.0 / (i + 1)})

    space = {"x": tune.uniform(-1, 1), "y": tune.uniform(-1, 1)}
    res = tune.Tuner(
        train_fn,
        tune_config=tune.TuneConfig(
            metric="loss", mode="min",
            search_alg=TuneBOHB(space, num_samples=20, n_startup=6,
                                seed=0),
            scheduler=HyperBandScheduler(metric="loss", mode="min",
                                         max_t=9, reduction_factor=3,
                                         num_brackets=2),
            max_concurrent_trials=4),
        run_config=RunConfig(name="bohb", storage_path=str(tmp_path))).fit()
    assert len(res) == 20
    # early stopping happened AND the search still found a good config
    iters = sorted(t.iterations for t in res.trials)
    assert iters[0] < 9
    assert res.get_best_result().metrics["loss"] < 0.6


def test_resource_changing_scheduler(tmp_path):
    """Trials see their reallocated bundle in config["trial_resources"]
    after a checkpointed runner restart (reference:
    tune/schedulers/resource_changing_scheduler.py)."""
    from ray_tpu.tune import ResourceChangingScheduler

    class Sizer(tune.Trainable):
        def setup(self, config):
            self.i = 0

        def step(self):
            self.i += 1
            res = self.config.get("trial_resources") or {}
            return {"iters": self.i, "res_cpu": res.get("CPU", 0),
                    "done": self.i >= 4}

        def save_checkpoint(self):
            return {"i": self.i}

        def load_checkpoint(self, ck):
            self.i = ck["i"]

    def grow(trial, result, live_trials, total_cpus):
        # deterministic allocator: always demand 2 CPUs
        return {"CPU": 2.0}

    sched = ResourceChangingScheduler(resources_allocation_function=grow)
    tuner = Tuner(
        Sizer,
        param_space={"x": tune.grid_search([1.0])},
        tune_config=TuneConfig(metric="iters", mode="max",
                               scheduler=sched, use_actors=False),
        run_config=RunConfig(name="rcs", storage_path=str(tmp_path)))
    grid = tuner.fit()
    t = grid.trials[0]
    assert t.status == "TERMINATED"
    assert t.resources == {"CPU": 2.0}
    # the restarted runner reported the new allocation
    assert t.last_result["res_cpu"] == 2.0
