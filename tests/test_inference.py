"""Inference-engine tests: KV-cache decode parity against the
full-recompute oracle, continuous-batching admission/eviction semantics,
slot-pool bounds, and metrics well-formedness.

Everything runs on CPU with GPTConfig.tiny (f32 activations so greedy
argmax parity is not at the mercy of bf16 ties)."""

import re
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.inference import (EngineConfig, InferenceEngine,
                               KVCacheManager)
from ray_tpu.models import gpt


@pytest.fixture(scope="module")
def cfg():
    return gpt.GPTConfig.tiny(dtype=jnp.float32, max_seq=64)


@pytest.fixture(scope="module")
def params(cfg):
    return gpt.init_params(cfg, jax.random.PRNGKey(0))


def _ref_tokens(params, cfg, prompt, max_new):
    """Greedy full-recompute oracle (models/gpt.generate)."""
    out = gpt.generate(params, cfg, jnp.asarray([prompt], jnp.int32),
                       max_new=max_new, temperature=0.0)
    return np.asarray(out)[0, len(prompt):].tolist()


@pytest.fixture
def engine(params, cfg):
    eng = InferenceEngine(params, cfg, EngineConfig(max_slots=2))
    yield eng
    eng.shutdown()


# --------------------------------------------------------------- cache pool

def test_cache_manager_alloc_free_exhaustion(cfg):
    mgr = KVCacheManager(cfg, n_slots=2, max_seq=32)
    a, b = mgr.alloc(), mgr.alloc()
    assert {a, b} == {0, 1}
    assert mgr.alloc() is None          # exhausted: caller must queue
    assert mgr.n_free == 0
    mgr.free(a)
    assert mgr.n_free == 1
    assert mgr.alloc() == a
    mgr.free(b)
    with pytest.raises(ValueError):     # double free
        mgr.free(b)


def test_cache_manager_bounds(cfg):
    with pytest.raises(ValueError):
        KVCacheManager(cfg, n_slots=0)
    with pytest.raises(ValueError):     # wider than the wpe table
        KVCacheManager(cfg, n_slots=1, max_seq=cfg.max_seq + 1)
    mgr = KVCacheManager(cfg, n_slots=4, max_seq=32)
    st = mgr.stats()
    assert st["bytes_total"] == 2 * int(np.prod(mgr.k.shape)) * 4  # f32
    assert st["free_slots"] == 4


# ------------------------------------------------------------------ parity

def test_greedy_kv_cache_parity_vs_full_recompute(engine, params, cfg):
    """The tentpole invariant: greedy KV-cache decode is token-identical
    to the full-recompute generate() oracle."""
    prompts = [[3, 1, 4, 1, 5], [9, 2, 6, 5, 3, 5, 8, 9, 7], [42]]
    for prompt in prompts:
        got = engine.generate(prompt, max_new=10, timeout=120)
        assert got == _ref_tokens(params, cfg, prompt, 10)


def test_prefill_logits_match_forward(params, cfg):
    """Right-padded prefill must produce the same next-token logits as
    an unpadded forward (causality makes the padding invisible)."""
    from ray_tpu.inference.decode import make_prefill_fn
    prefill = make_prefill_fn(cfg)
    prompt = np.array([3, 1, 4, 1, 5, 9, 2, 6], np.int32)
    n, S = len(prompt), 32
    padded = np.zeros((1, S), np.int32)
    padded[0, :n] = prompt
    logits, k, v = prefill(params, padded)
    ref = gpt.forward(params, jnp.asarray(prompt)[None], cfg)
    np.testing.assert_allclose(np.asarray(logits)[0, n - 1],
                               np.asarray(ref)[0, -1], atol=1e-4)
    assert k.shape == (cfg.n_layers, 1, cfg.n_heads, S, cfg.head_dim)


def test_attention_kv_lengths_masks_per_row():
    """ops/attention kv_lengths == explicit per-row mask."""
    from ray_tpu.ops.attention import mha_reference
    rng = jax.random.PRNGKey(0)
    q = jax.random.normal(rng, (3, 2, 1, 8))
    k = jax.random.normal(jax.random.PRNGKey(1), (3, 2, 6, 8))
    v = jax.random.normal(jax.random.PRNGKey(2), (3, 2, 6, 8))
    lengths = jnp.array([1, 3, 6])
    got = mha_reference(q, k, v, causal=False, kv_lengths=lengths)
    mask = (jnp.arange(6)[None, :] < lengths[:, None])[:, None, None, :]
    ref = mha_reference(q, k, v, causal=False,
                        mask=jnp.broadcast_to(mask, (3, 2, 1, 6)))
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-6)


def test_sample_token_shared_head():
    logits = jnp.asarray([[0.1, 3.0, -1.0], [2.0, 0.0, 1.0]])
    assert gpt.sample_token(logits, temperature=0.0).tolist() == [1, 0]
    tok = gpt.sample_token(logits[0], temperature=1.0,
                           rng=jax.random.PRNGKey(0))
    assert 0 <= int(tok) < 3
    with pytest.raises(ValueError):
        gpt.sample_token(logits, temperature=0.5)   # rng required


# --------------------------------------------------- continuous batching

def test_admission_mid_decode_isolated(engine, params, cfg):
    """Request B joins while A decodes; both finish with oracle-exact
    tokens — B's admission must not perturb A's cache rows and vice
    versa (slot masking)."""
    pa, pb = [3, 1, 4, 1, 5], [9, 2, 6, 5, 3, 5, 8]
    ra = engine.submit(pa, max_new=24)
    stream = ra.stream(timeout=120)
    first = [next(stream) for _ in range(4)]      # A is mid-decode...
    rb = engine.submit(pb, max_new=6)             # ...when B is admitted
    assert ra.result(timeout=120) == _ref_tokens(params, cfg, pa, 24)
    assert rb.result(timeout=120) == _ref_tokens(params, cfg, pb, 6)
    assert first == _ref_tokens(params, cfg, pa, 24)[:4]


def test_slot_exhaustion_queues(params, cfg):
    """With one slot, a second request parks in the admission queue (no
    memory growth) and runs after the first evicts."""
    eng = InferenceEngine(params, cfg, EngineConfig(max_slots=1))
    try:
        ra = engine_a = eng.submit([1, 2, 3], max_new=40)
        rb = eng.submit([4, 5, 6], max_new=5)
        saw_waiting = False
        deadline = time.time() + 60
        while time.time() < deadline:
            st = eng.stats()
            if st["waiting_requests"] >= 1 and st["active_slots"] == 1:
                saw_waiting = True
                break
            if rb.done:
                break
            time.sleep(0.002)
        assert saw_waiting, "second request never observed queued"
        assert ra.result(timeout=120) == _ref_tokens(params, cfg,
                                                     [1, 2, 3], 40)
        assert rb.result(timeout=120) == _ref_tokens(params, cfg,
                                                     [4, 5, 6], 5)
        assert eng.stats()["free_slots"] == 1
    finally:
        eng.shutdown()


def test_eos_eviction_frees_slot(params, cfg):
    ref = _ref_tokens(params, cfg, [7, 8, 9], 8)
    eng = InferenceEngine(params, cfg,
                          EngineConfig(max_slots=2, eos_token=ref[0]))
    try:
        out = eng.generate([7, 8, 9], max_new=8, timeout=120)
        assert out == [ref[0]]            # stopped at EOS, not max_new
        st = eng.stats()
        assert st["active_slots"] == 0 and st["free_slots"] == 2
        # the freed slot is immediately reusable
        out2 = eng.generate([7, 8, 9], max_new=8, timeout=120)
        assert out2 == [ref[0]]
    finally:
        eng.shutdown()


def test_max_tokens_eviction_and_slot_reuse(engine, params, cfg):
    """More requests than slots, all complete (slots recycle)."""
    prompts = [[i + 1, i + 2] for i in range(5)]
    reqs = [engine.submit(p, max_new=4) for p in prompts]
    for p, r in zip(prompts, reqs):
        assert r.result(timeout=120) == _ref_tokens(params, cfg, p, 4)
    st = engine.stats()
    assert st["requests_completed"] >= 5
    assert st["free_slots"] == st["max_slots"]


def test_temperature_sampling_in_range(engine, cfg):
    out = engine.generate([1, 2, 3], max_new=12, temperature=1.0, seed=7,
                          timeout=120)
    assert len(out) == 12
    assert all(0 <= t < cfg.vocab_size for t in out)


def test_submit_validation(engine, cfg):
    with pytest.raises(ValueError):
        engine.submit([], max_new=4)
    with pytest.raises(ValueError):
        engine.submit([1, 2], max_new=0)
    with pytest.raises(ValueError):
        engine.submit([cfg.vocab_size + 5], max_new=4)
    with pytest.raises(ValueError):                 # overflows the cache
        engine.submit([1] * 60, max_new=60)
    with pytest.raises(NotImplementedError):        # no MoE decode path
        from ray_tpu.inference.decode import make_decode_step
        make_decode_step(gpt.GPTConfig.tiny_moe())


def test_shutdown_fails_pending(params, cfg):
    eng = InferenceEngine(params, cfg, EngineConfig(max_slots=1))
    r = eng.submit([1, 2, 3], max_new=50)
    eng.shutdown()
    with pytest.raises(RuntimeError):
        r.result(timeout=10)            # failed, not silently dropped
    with pytest.raises(RuntimeError):
        eng.submit([4], max_new=2)


def test_cancel_waiting_and_active_frees_slots(params, cfg):
    """cancel() drops a queued request before admission and evicts an
    active one at the next iteration — abandoned work never holds a slot
    against live requests."""
    eng = InferenceEngine(params, cfg, EngineConfig(max_slots=1))
    try:
        ra = eng.submit([1, 2, 3], max_new=40)
        rb = eng.submit([4, 5, 6], max_new=40)   # parked: no free slot
        rb.cancel()
        ra.cancel()
        ra.result(timeout=60)
        rb.result(timeout=60)
        assert ra.done and rb.done
        deadline = time.time() + 30
        while eng.stats()["free_slots"] != 1 and time.time() < deadline:
            time.sleep(0.005)
        assert eng.stats()["free_slots"] == 1
        # live work proceeds on the freed slot
        out = eng.generate([7, 8], max_new=3, timeout=120)
        assert out == _ref_tokens(params, cfg, [7, 8], 3)
    finally:
        eng.shutdown()


def test_admit_failure_isolated_no_slot_leak(params, cfg):
    """Slot mode: a prefill failure fails ONE request, returns its slot,
    and the engine keeps serving (no pool shrinkage, no busy-spin).
    (The paged path's prefill DONATES the pool, so its failure semantics
    are recovery, not isolation — test_paged_cache.py covers that.)"""
    eng = InferenceEngine(params, cfg,
                          EngineConfig(max_slots=2, paged=False))
    try:
        real_prefill = eng._prefill
        boom = {"armed": True}

        def failing_prefill(params_, tokens):
            if boom.pop("armed", False):
                raise RuntimeError("injected prefill failure")
            return real_prefill(params_, tokens)

        eng._prefill = failing_prefill
        bad = eng.submit([1, 2], max_new=4)
        with pytest.raises(RuntimeError, match="injected"):
            bad.result(timeout=60)
        assert eng.stats()["free_slots"] == 2      # slot came back
        out = eng.generate([3, 4], max_new=4, timeout=120)
        assert out == _ref_tokens(params, cfg, [3, 4], 4)
    finally:
        eng.shutdown()


def test_slot_mode_parity_and_reuse(params, cfg):
    """The legacy slot engine (paged=False — the serving benchmark's
    same-run A/B baseline) keeps oracle parity and slot recycling."""
    eng = InferenceEngine(params, cfg,
                          EngineConfig(max_slots=2, paged=False))
    try:
        assert eng.stats()["paged"] is False
        prompts = [[3, 1, 4, 1, 5], [9, 2, 6], [11, 12]]
        reqs = [eng.submit(p, max_new=6) for p in prompts]
        for p, r in zip(prompts, reqs):
            assert r.result(timeout=120) == _ref_tokens(params, cfg, p, 6)
        assert eng.stats()["free_slots"] == 2
    finally:
        eng.shutdown()


def test_cancelled_waiters_reaped_while_pool_full(params, cfg):
    """Cancelled queued requests are reaped even when no slot is free —
    zombies must not consume max_waiting backpressure."""
    eng = InferenceEngine(params, cfg, EngineConfig(max_slots=1))
    try:
        ra = eng.submit([1, 2, 3], max_new=50)     # holds the only slot
        zombies = [eng.submit([4, 5], max_new=50) for _ in range(3)]
        for z in zombies:
            z.cancel()
        deadline = time.time() + 30
        while time.time() < deadline:
            st = eng.stats()
            if st["waiting_requests"] == 0 and st["active_slots"] == 1:
                break
            time.sleep(0.005)
        st = eng.stats()
        assert st["waiting_requests"] == 0 and st["active_slots"] == 1
        for z in zombies:
            z.result(timeout=30)                   # finished, not hung
        ra.cancel()
    finally:
        eng.shutdown()


def test_step_failure_fails_inflight_and_recovers(params, cfg):
    """A decode-step failure fails the in-flight requests AND reallocates
    the (donated) cache arrays so the engine keeps serving."""
    eng = InferenceEngine(params, cfg, EngineConfig(max_slots=2))
    try:
        real_step = eng._step
        boom = {"armed": True}

        def failing_step(*a):
            if boom.pop("armed", False):
                raise RuntimeError("injected step failure")
            return real_step(*a)

        eng._step = failing_step
        bad = eng.submit([1, 2], max_new=8)
        with pytest.raises(RuntimeError, match="injected"):
            bad.result(timeout=60)
        out = eng.generate([3, 4], max_new=4, timeout=120)
        assert out == _ref_tokens(params, cfg, [3, 4], 4)
        assert eng.stats()["free_slots"] == 2
    finally:
        eng.shutdown()


def test_result_timeout_zero_raises(engine):
    r = engine.submit([1, 2, 3], max_new=30)
    with pytest.raises(TimeoutError):
        r.result(timeout=0)
    r.cancel()


def test_abandoned_engine_is_collectable(params, cfg):
    """Dropping every reference without shutdown() must let the engine
    (KV pool + loop thread) die: the loop thread only holds it weakly
    between passes."""
    import gc
    import weakref as _weakref
    eng = InferenceEngine(params, cfg, EngineConfig(max_slots=1))
    eng.generate([1, 2], max_new=2, timeout=120)
    thread = eng._thread
    ref = _weakref.ref(eng)
    del eng
    deadline = time.time() + 30
    while ref() is not None and time.time() < deadline:
        gc.collect()
        time.sleep(0.01)
    assert ref() is None, "engine leaked after last reference dropped"
    thread.join(timeout=30)
    assert not thread.is_alive()


def test_replica_teardown_stops_engine(params, cfg):
    """Scaling a serve replica away must shut its engine down (thread +
    KV pool released), via the _InProcReplica.close → teardown hook."""
    from ray_tpu import serve as serve_mod
    from ray_tpu.inference import build_gpt_deployment

    dep = build_gpt_deployment(cfg=cfg, engine_cfg=EngineConfig(max_slots=2),
                               seed=0, params=params)
    try:
        h = serve_mod.run(dep, use_actors=False)
        from ray_tpu.inference.engine import _ENGINES
        names = [n for n, e in _ENGINES.items() if not e._stopped]
        assert names, "replica engine not registered"
        serve_mod.status()   # deployment is live
        serve_mod.get_handle("v1")._state.scale_to(0)
        assert all(_ENGINES[n]._stopped for n in names
                   if n in _ENGINES)
    finally:
        serve_mod.shutdown()


# ----------------------------------------------------------------- metrics

# one Prometheus exposition sample: name{labels} value
_SAMPLE_RE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="[^"]*")*\})? '
    r'[-+]?((\d+(\.\d+)?([eE][-+]?\d+)?)|Inf|NaN)$')


def test_engine_metrics_wellformed(engine):
    """Per-engine gauges render as valid Prometheus exposition — the
    inference-side companion of the flight-recorder histogram test."""
    from ray_tpu import inference
    from ray_tpu.metrics import render_prometheus
    engine.generate([1, 2, 3], max_new=6, timeout=120)
    snap = inference.metrics_snapshot()
    names = {t[0] for t in snap}
    assert {"ray_tpu_inference_active_slots",
            "ray_tpu_inference_waiting_requests",
            "ray_tpu_inference_batch_occupancy_ratio",
            "ray_tpu_inference_generated_tokens_total",
            "ray_tpu_inference_requests_completed_total"} <= names
    text = render_prometheus(snap)
    help_seen, type_seen, samples = set(), set(), 0
    for line in text.strip().splitlines():
        if line.startswith("# HELP "):
            help_seen.add(line.split()[2])
        elif line.startswith("# TYPE "):
            name, kind = line.split()[2:4]
            type_seen.add(name)
            assert kind in ("gauge", "counter", "histogram")
        else:
            assert _SAMPLE_RE.match(line), f"malformed sample: {line!r}"
            samples += 1
    assert help_seen == type_seen == names
    assert samples >= len(names)
    # this engine's series carries its label and real counts
    assert f'engine="{engine.name}"' in text
    st = engine.stats()
    assert st["generated_tokens"] >= 6
    assert 0.0 <= st["batch_occupancy"] <= 1.0
