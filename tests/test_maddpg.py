"""MADDPG tests (reference test model:
rllib/algorithms/maddpg/tests/test_maddpg.py)."""

import numpy as np
import pytest

from ray_tpu.rllib.maddpg import MADDPG, MADDPGConfig, SpreadLine


def test_spread_line_env_contract():
    env = SpreadLine(num_agents=3, seed=0)
    obs = env.reset()
    assert len(obs) == 3 and obs["agent_0"].shape == (4,)
    o, r, d, _ = env.step({a: np.asarray([0.5]) for a in env.agent_ids})
    # shared (cooperative) reward
    assert len(set(r.values())) == 1
    assert "__all__" in d


def test_maddpg_step_and_checkpoint():
    algo = MADDPGConfig(num_agents=2, rollout_length=64,
                        learning_starts=32, batch_size=16,
                        seed=0).build()
    r = algo.train()
    assert r["steps_this_iter"] == 64 and r["buffer_size"] == 64
    assert np.isfinite(r["critic_loss"])
    import jax
    ck = algo.save_checkpoint()
    before = jax.tree.map(np.asarray, algo.state)
    algo.train()
    algo.load_checkpoint(ck)
    after = jax.tree.map(np.asarray, algo.state)
    for a, b in zip(jax.tree.leaves(before), jax.tree.leaves(after)):
        np.testing.assert_allclose(a, b)


@pytest.mark.slow
def test_maddpg_improves_coverage():
    algo = MADDPGConfig(num_agents=2, rollout_length=200,
                        learning_starts=200, batch_size=64,
                        seed=0).build()
    returns = []
    for _ in range(8):
        algo.train()
        if algo._ep_returns:
            returns.append(float(np.mean(algo._ep_returns[-20:])))
    # centralized critics should beat the random-walk baseline clearly
    assert returns[-1] > returns[0] + 3.0, \
        f"MADDPG no improvement: {returns[0]} -> {returns[-1]}"
