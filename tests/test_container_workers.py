"""Containerized workers: runtime_env.container now reaches the node's
SPAWN path (ROADMAP 5a closed — validation + argv building existed, but
no spawn ever exec'd it).  Tested through a stubbed ``podman`` on PATH,
the launcher's stubbed-gcloud pattern: the stub records the argv it was
handed, then execs the worker command with the image env applied — so
the task genuinely runs inside the container argv.
"""

from __future__ import annotations

import json
import os
import stat

import pytest

import ray_tpu

IMAGE = "fake.registry/chaos-img:1"

_STUB = """#!/usr/bin/env python3
import json, os, sys
args = sys.argv[1:]
with open({log!r}, "a") as f:
    f.write(json.dumps(args) + "\\n")
image = next(a.split("=", 1)[1] for a in args
             if a.startswith("RAY_TPU_CONTAINER_IMAGE="))
cmd = args[args.index(image) + 1:]
os.environ["RAY_TPU_CONTAINER_IMAGE"] = image
os.execvp(cmd[0], cmd)
"""


@pytest.fixture
def podman_stub(tmp_path, monkeypatch):
    log = tmp_path / "podman_calls.jsonl"
    bindir = tmp_path / "bin"
    bindir.mkdir()
    stub = bindir / "podman"
    stub.write_text(_STUB.format(log=str(log)))
    stub.chmod(stub.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("PATH",
                       f"{bindir}{os.pathsep}{os.environ.get('PATH', '')}")
    return log


@pytest.fixture
def rt(podman_stub):
    ray_tpu.init(num_cpus=2, num_tpus=0)
    yield ray_tpu
    ray_tpu.shutdown()


def test_task_runs_inside_container_argv(rt, podman_stub):
    @ray_tpu.remote(runtime_env={"container": {"image": IMAGE}})
    def where_am_i():
        return {"image": os.environ.get("RAY_TPU_CONTAINER_IMAGE", ""),
                "pid": os.getpid()}

    out = ray_tpu.get(where_am_i.remote(), timeout=120)
    # the worker really came up through the container argv: the image
    # env only exists inside the stub-exec'd command
    assert out["image"] == IMAGE

    calls = [json.loads(line)
             for line in podman_stub.read_text().splitlines()]
    assert calls, "podman was never invoked"
    argv = calls[0]
    assert argv[0] == "run"
    assert "--network=host" in argv and "--ipc=host" in argv
    # --pid=host: the registered worker pid must be signalable by the
    # node (OOM kills, stack dumps, chaos kills)
    assert "--pid=host" in argv
    assert IMAGE in argv
    worker_cmd = argv[argv.index(IMAGE) + 1:]
    # prefork bypass: a template fork can't exec inside an image, so
    # the spawn must be the cold worker argv wrapped by the runtime
    assert "ray_tpu.core.worker" in worker_cmd


def test_plain_tasks_do_not_borrow_container_workers(rt, podman_stub):
    @ray_tpu.remote(runtime_env={"container": {"image": IMAGE}})
    def containered():
        return os.environ.get("RAY_TPU_CONTAINER_IMAGE", "")

    @ray_tpu.remote
    def plain():
        return os.environ.get("RAY_TPU_CONTAINER_IMAGE", "")

    assert ray_tpu.get(containered.remote(), timeout=120) == IMAGE
    # a host task scheduled right after must not land in the (now
    # idle) containerized worker
    assert ray_tpu.get(plain.remote(), timeout=120) == ""


def test_container_validation_still_guards_bad_shapes():
    from ray_tpu.runtime_env import validate
    with pytest.raises(ValueError):
        validate({"container": {"run_options": ["x"]}})   # no image
    ok = validate({"container": {"image": IMAGE,
                                 "run_options": ["--cap-add=NET_ADMIN"]}})
    assert ok["container"]["image"] == IMAGE
