"""C++ worker API build + run (reference analogue: cpp/ api tests run
in CI; here the Makefile target builds against the same shm store the
Python runtime uses)."""

import shutil
import subprocess

import pytest

NATIVE = __file__.rsplit("/", 2)[0] + "/native"


@pytest.mark.skipif(shutil.which("g++") is None, reason="no C++ toolchain")
def test_cpp_api_build_and_run():
    out = subprocess.run(["make", "-C", NATIVE, "api_test"],
                         capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "api_test ok" in out.stdout


@pytest.mark.skipif(shutil.which("g++") is None, reason="no C++ toolchain")
def test_cpp_race_test():
    out = subprocess.run(["make", "-C", NATIVE, "race"],
                         capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "race_test ok" in out.stdout
