"""Operator-graph streaming executor (reference:
_internal/execution/streaming_executor.py:31, operators/
task_pool_map_operator.py, actor_pool_map_operator.py).

The round-5 "done" criterion: a 3-stage pipeline (read -> actor-pool
cpu map -> sharded device feed) streams a dataset larger than the
object store budget with bounded peak usage and per-operator stats.
"""

import numpy as np
import pytest

import ray_tpu


STORE_BUDGET = 48 * 1024 * 1024


@pytest.fixture(scope="module")
def rt():
    ray_tpu.init(num_cpus=4, num_tpus=0,
                 object_store_memory=STORE_BUDGET)
    yield ray_tpu
    ray_tpu.shutdown()


def _big_dataset(n_blocks=40, rows_per_block=32_768):
    """float32 x-column blocks (rows_per_block * 4 B + index column)."""
    from ray_tpu.data import Dataset
    blocks = [{"x": np.full(rows_per_block, float(i), dtype=np.float32),
               "i": np.full(rows_per_block, i, dtype=np.int64)}
              for i in range(n_blocks)]
    return Dataset(blocks)


def test_operator_chain_compilation(rt):
    from ray_tpu.data import Dataset
    from ray_tpu.data.execution import (ActorPoolMapOperator,
                                        TaskMapOperator,
                                        build_operator_chain)

    ds = (Dataset.range(10)
          .map_batches(lambda b: b)                       # tasks
          .map_batches(lambda b: b)                       # tasks (fused)
          .map_batches(lambda b: b, compute="actors",
                       num_actors=3)                      # actor pool
          .map_batches(lambda b: b))                      # tasks again
    ops = build_operator_chain(ds._stages)
    kinds = [type(o).__name__ for o in ops]
    assert kinds == ["TaskMapOperator", "ActorPoolMapOperator",
                     "TaskMapOperator"]
    assert isinstance(ops[1], ActorPoolMapOperator)
    assert isinstance(ops[0], TaskMapOperator)
    assert len(ops[0]._stages) == 2     # consecutive task stages fused


def test_larger_than_store_stream_bounded(rt):
    """30 x ~4.3 MiB blocks (~130 MiB plus intermediate copies) through
    a 48 MiB store: per-op budgets + eager release of consumed
    intermediates keep peak usage inside the budget — nothing spills."""
    from ray_tpu.core.runtime import get_runtime
    from ray_tpu.data.execution import (StreamingExecutor,
                                        build_operator_chain)

    svc = get_runtime().node_service
    spills_before = svc.store.stats()["num_spilled"]

    rows = 1 << 19   # ~6 MiB/block (f32 x + i64 i); 30 blocks ≈ 180 MiB
    ds = (_big_dataset(n_blocks=30, rows_per_block=rows)
          .map_batches(lambda b: {"x": b["x"] * 2})
          .map_batches(lambda b: {"x": b["x"] + 1},
                       compute="actors", num_actors=1,
                       max_tasks_per_actor=1))
    ops = build_operator_chain(ds._stages, max_in_flight=1)
    ex = StreamingExecutor(ops)

    total = 0.0
    n = 0
    for blk in ex.execute(ds._resolve_blocks()):
        total += float(blk["x"].sum())
        n += 1
    assert n == 30
    expect = sum((2.0 * i + 1) * rows for i in range(30))
    assert total == expect

    # per-operator stats exist and reflect the run
    stats = ex.stats()
    assert [s["operator"] for s in stats] == ["map(tasks)",
                                              "map(actors x1)"]
    for s in stats:
        assert s["inputs"] == s["outputs"] == 30
        assert s["submitted"] == 30

    # Bounded-usage claim: ~390 MiB of blocks+intermediates moved
    # through a 48 MiB store.  Full materialization would spill ~57
    # blocks; streaming's only spills are first-fit arena fragmentation
    # relief (single-digit, alternating 6/2 MiB alloc-free pattern).
    spilled = svc.store.stats()["num_spilled"] - spills_before
    assert spilled <= 15, f"stream not bounded: {spilled} spills"
    # consumed blocks were released, not retained.  The native arena
    # defers reclaim of released blocks while zero-copy views are alive
    # and drains them under allocation pressure — drain explicitly here
    # (gc first: the consumer's numpy views must die) to observe it.
    import gc
    import time
    deadline = time.time() + 10
    while time.time() < deadline:
        gc.collect()
        drain = getattr(svc.store, "_drain_pending_deletes", None)
        if drain is not None:
            drain()
        if svc.store.stats()["used_bytes"] < STORE_BUDGET // 2:
            break
        time.sleep(0.3)
    assert svc.store.stats()["used_bytes"] < STORE_BUDGET // 2


def test_backpressure_bounds_in_flight(rt):
    """A deliberately slow consumer must throttle submission — no more
    than the per-op budget is ever in flight."""
    import time
    from ray_tpu.data.execution import (StreamingExecutor,
                                        build_operator_chain)

    ds = _big_dataset(n_blocks=12).map_batches(
        lambda b: {"x": b["x"] * 3, "i": b["i"]})
    ops = build_operator_chain(ds._stages, max_in_flight=2)
    ex = StreamingExecutor(ops)
    got = 0
    for _blk in ex.execute(ds._resolve_blocks()):
        time.sleep(0.05)     # slow sink
        got += 1
    assert got == 12
    assert ex.stats()[0]["peak_in_flight"] <= 2


def test_streaming_device_feed_three_stages(rt):
    """read -> actor-pool map -> sharded device feed: the full TPU
    ingest shape on the virtual CPU mesh."""
    import jax
    from ray_tpu.parallel.mesh import create_mesh

    mesh = create_mesh({"dp": 4}, devices=jax.devices("cpu")[:4])
    ds = (_big_dataset(n_blocks=8, rows_per_block=4096)
          .map_batches(lambda b: {"x": b["x"] * 2},
                       compute="actors", num_actors=2))

    seen = 0
    for batch in ds.iter_batches_sharded(mesh, batch_size=512,
                                         parallelism="streaming"):
        assert batch["x"].shape == (512,)
        # sharded over the mesh's data axis
        assert len(batch["x"].sharding.device_set) == 4
        seen += 1
    assert seen == 8 * 4096 // 512


def test_actor_pool_operator_shuts_down_actors(rt):
    from ray_tpu.data.execution import (StreamingExecutor,
                                        build_operator_chain)
    from ray_tpu.core.runtime import get_runtime

    svc = get_runtime().node_service
    before = sum(1 for a in svc.actors.values() if a.state == "alive")
    ds = _big_dataset(n_blocks=6, rows_per_block=1024).map_batches(
        lambda b: b, compute="actors", num_actors=2)
    ops = build_operator_chain(ds._stages)
    list(StreamingExecutor(ops).execute(ds._resolve_blocks()))
    import time
    deadline = time.time() + 30
    while time.time() < deadline:
        alive = sum(1 for a in svc.actors.values() if a.state == "alive")
        if alive <= before:
            return
        time.sleep(0.2)
    raise AssertionError(f"pool actors leaked: {alive} > {before}")
