"""Speculative decoding tests: greedy token parity vs the
full-recompute oracle for BOTH drafters (n-gram prompt-lookup and
truncated-layer self-draft) under prefix reuse, chunked prefill,
block-pressure preemption mid-speculation, and verify-step failure
recovery; block-refcount audits proving reject rollback leaks zero
blocks; the typed SpeculationUnsupported boundary and the documented
temperature fallback; the infer_speculate chaos point (forced full
rejection and injected verify failure); and the accept-rate /
tokens-per-step metric surface.

Everything runs on CPU with GPTConfig.tiny at f32 (greedy argmax
parity must not hinge on bf16 ties)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.inference import (EngineConfig, InferenceEngine,
                               SpeculationUnsupported, metrics_snapshot,
                               ngram_propose)
from ray_tpu.models import gpt


@pytest.fixture(scope="module")
def cfg():
    return gpt.GPTConfig.tiny(dtype=jnp.float32, max_seq=64)


@pytest.fixture(scope="module")
def params(cfg):
    return gpt.init_params(cfg, jax.random.PRNGKey(0))


def _ref_tokens(params, cfg, prompt, max_new):
    out = gpt.generate(params, cfg, jnp.asarray([prompt], jnp.int32),
                       max_new=max_new, temperature=0.0)
    return np.asarray(out)[0, len(prompt):].tolist()


def _spec_cfg(mode, **kw):
    base = dict(max_slots=4, kv_block_size=8, prefill_chunk=16,
                speculate=mode, speculate_k=4)
    if mode == "self":
        base["draft_layers"] = 1
    base.update(kw)
    return EngineConfig(**base)


def _assert_no_block_leak(st):
    assert st["blocks_free"] + st["prefix_cached_blocks"] \
        == st["blocks_total"], f"block leak: {st}"


# ------------------------------------------------------ n-gram drafter


def test_ngram_propose_matches_repeated_pattern():
    ctx = np.array([7, 1, 2, 3, 9, 1, 2, 3], np.int32)
    # suffix [1,2,3] last matched at s=1 -> continuation [9, 1, 2, ...]
    prop = ngram_propose(ctx, 3)
    assert prop.tolist() == [9, 1, 2]


def test_ngram_propose_prefers_longest_match_and_latest_site():
    ctx = np.array([1, 2, 5, 3, 2, 6, 3, 2], np.int32)
    # 2-gram [3,2] matches at s=3 -> continuation starts with 6; the
    # 1-gram [2] would have matched later but the longer match wins
    assert ngram_propose(ctx, 2).tolist() == [6, 3]


def test_ngram_propose_no_match_is_empty():
    ctx = np.array([1, 2, 3, 4, 5], np.int32)
    assert ngram_propose(ctx, 4).size == 0
    assert ngram_propose(np.array([1], np.int32), 4).size == 0
    assert ngram_propose(np.array([], np.int32), 4).size == 0


def test_ngram_propose_caps_at_k_and_history_end():
    ctx = np.array([1, 2, 1, 2, 1, 2], np.int32)
    assert ngram_propose(ctx, 2).size <= 2
    # match near the end: continuation shorter than k is fine
    prop = ngram_propose(np.array([5, 6, 7, 5, 6], np.int32), 8)
    assert prop.tolist() == [7, 5, 6]


# --------------------------------------------- parity: the tentpole


@pytest.mark.parametrize("mode", ["ngram", "self"])
def test_spec_parity_prefix_reuse_and_chunked_prefill(params, cfg, mode):
    """THE speculation invariant (tier-1): greedy decode with
    draft-then-verify — under paging, radix prefix reuse, and chunked
    prefill — is token-identical to the full-recompute oracle, while
    actually speculating (accepted tokens > 0)."""
    eng = InferenceEngine(params, cfg, _spec_cfg(mode))
    try:
        rng = np.random.default_rng(7)
        head = rng.integers(0, cfg.vocab_size, 24).tolist()   # 3 blocks
        prompts = ([head + rng.integers(0, cfg.vocab_size,
                                        int(rng.integers(2, 10))).tolist()
                    for _ in range(3)]
                   + [[1, 2, 3, 4] * 6]                # n-gram gold
                   + [rng.integers(0, cfg.vocab_size, 40).tolist()])
        for wave in ("cold", "warm"):
            reqs = [eng.submit(p, max_new=8) for p in prompts]
            for p, r in zip(prompts, reqs):
                assert r.result(timeout=300) == \
                    _ref_tokens(params, cfg, p, 8), (mode, wave, p)
        st = eng.stats()
        assert st["speculate"] == mode
        assert st["spec_passes"] > 0
        assert st["spec_drafted_tokens"] > 0
        assert st["spec_accepted_tokens"] > 0
        assert st["prefix_hit_tokens"] > 0        # warm wave adopted heads
        # per-row throughput: > 1 token per (row, compiled call) pair is
        # the whole point; the plain engine reports exactly 1.0 here
        assert st["tokens_per_step"] > 1.0
        _assert_no_block_leak(st)
    finally:
        eng.shutdown()


def test_spec_parity_under_preemption_refunds_charge(params, cfg):
    """Block pressure preempts a row that holds a speculative charge:
    the charged blocks joined the row's chain at grant time, so the
    preemption refund covers them automatically — streams stay
    oracle-exact and the pool audits clean."""
    eng = InferenceEngine(params, cfg, EngineConfig(
        max_slots=4, max_seq=32, kv_block_size=8, n_blocks=6,
        prefill_chunk=16, speculate="self", draft_layers=1,
        speculate_k=4))
    try:
        rng = np.random.default_rng(1)
        jobs = []
        for _ in range(6):
            p = rng.integers(0, cfg.vocab_size,
                             int(rng.integers(6, 20))).tolist()
            jobs.append((p, eng.submit(p, max_new=12)))
        for p, h in jobs:
            assert h.result(timeout=300) == _ref_tokens(params, cfg, p, 12)
        st = eng.stats()
        assert st["preemptions"] > 0, \
            "pool of 6 blocks under 6 concurrent requests never preempted"
        assert st["spec_drafted_tokens"] > 0, "never speculated"
        _assert_no_block_leak(st)
    finally:
        eng.shutdown()


def test_spec_verify_failure_recovers_pool_and_prefix(params, cfg):
    """A verify-step failure takes the same recovery path as a plain
    step failure: in-flight requests fail typed, the donated pool is
    reallocated, the prefix index is cleared, and the engine keeps
    serving with oracle parity."""
    eng = InferenceEngine(params, cfg, _spec_cfg("ngram"))
    try:
        rep = [1, 2, 3, 4] * 6                   # n-gram drafts for sure
        assert eng.generate(rep, max_new=4, timeout=300) \
            == _ref_tokens(params, cfg, rep, 4)

        real_verify = eng._verify
        boom = {"armed": True}

        def failing_verify(*a):
            if boom.pop("armed", False):
                raise RuntimeError("injected verify failure")
            return real_verify(*a)

        eng._verify = failing_verify
        bad = eng.submit(rep, max_new=8)
        with pytest.raises(RuntimeError, match="injected verify"):
            bad.result(timeout=60)
        st = eng.stats()
        assert st["prefix_cached_blocks"] == 0       # index cleared
        assert st["blocks_free"] == st["blocks_total"]
        assert eng.generate(rep, max_new=4, timeout=300) \
            == _ref_tokens(params, cfg, rep, 4)
    finally:
        eng.shutdown()


# --------------------------------------------------- chaos: infer_speculate


def test_chaos_forced_rejection_keeps_parity_and_blocks(params, cfg):
    """The registered infer_speculate gate: scripted FULL rejection of
    every draft still verifies, emits the plain step's token (parity),
    and rolls the speculative block charge back without leaking."""
    from ray_tpu.core import fault_injection as fi

    eng = InferenceEngine(params, cfg, _spec_cfg("ngram"))
    plan = fi.FaultPlan()
    plan.add(fi.Rule("infer_speculate", "script",
                     fn=lambda ctx: ctx.__setitem__("reject_all", True)))
    fi.install(plan)
    try:
        rep = [1, 2, 3, 4] * 6
        assert eng.generate(rep, max_new=8, timeout=300) \
            == _ref_tokens(params, cfg, rep, 8)
        assert any(p == "infer_speculate" for p, _, _ in plan.log)
        st = eng.stats()
        assert st["spec_drafted_tokens"] > 0         # drafts were offered
        assert st["spec_accepted_tokens"] == 0       # ... all rejected
        assert st["spec_accept_rate"] == 0.0
        _assert_no_block_leak(st)
    finally:
        fi.uninstall()
        eng.shutdown()


def test_chaos_speculate_raise_takes_recovery_path(params, cfg):
    """Raising from the infer_speculate hook injects a failure at the
    draft/verify choke point; the engine fails in-flight work typed and
    keeps serving."""
    from ray_tpu.core import fault_injection as fi

    eng = InferenceEngine(params, cfg, _spec_cfg("ngram"))
    plan = fi.FaultPlan()

    def raiser(ctx):
        raise RuntimeError("injected speculation failure")

    plan.add(fi.Rule("infer_speculate", "script", fn=raiser, nth=1))
    fi.install(plan)
    try:
        rep = [1, 2, 3, 4] * 6
        bad = eng.submit(rep, max_new=8)
        with pytest.raises(RuntimeError, match="injected speculation"):
            bad.result(timeout=60)
    finally:
        fi.uninstall()
    try:
        rep = [1, 2, 3, 4] * 6
        assert eng.generate(rep, max_new=4, timeout=300) \
            == _ref_tokens(params, cfg, rep, 4)
    finally:
        eng.shutdown()


# ------------------------------------- typed boundary + temperature


def test_speculation_unsupported_is_typed_and_construction_time(params,
                                                                cfg):
    """The capability boundary raises at engine CONSTRUCTION, like
    MoEDecodeUnsupported — never mid-decode with slots held."""
    with pytest.raises(SpeculationUnsupported):
        InferenceEngine(params, cfg, EngineConfig(
            max_slots=2, paged=False, speculate="ngram"))
    # bad draft_layers: 0 and >= n_layers have no truncated model
    with pytest.raises(SpeculationUnsupported):
        InferenceEngine(params, cfg, _spec_cfg("self", draft_layers=0))
    with pytest.raises(SpeculationUnsupported):
        InferenceEngine(params, cfg, _spec_cfg(
            "self", draft_layers=cfg.n_layers))
    with pytest.raises(ValueError):
        InferenceEngine(params, cfg, EngineConfig(
            max_slots=2, speculate="medusa"))
    with pytest.raises(ValueError):
        InferenceEngine(params, cfg, _spec_cfg("ngram", speculate_k=0))


def test_temperature_rows_fall_back_transparently(params, cfg):
    """The decided temperature policy (documented on submit()): sampled
    rows ride the verify pass one token at a time — they never draft —
    while greedy neighbors in the SAME batch keep full parity.  No
    error, no silent parity break."""
    eng = InferenceEngine(params, cfg, _spec_cfg("ngram"))
    try:
        rep = [1, 2, 3, 4] * 6
        plain = [9, 8, 7, 6, 5]
        greedy1 = eng.submit(rep, max_new=8)
        sampled = eng.submit(plain, max_new=8, temperature=0.9, seed=3)
        greedy2 = eng.submit(list(reversed(rep)), max_new=8)
        assert greedy1.result(timeout=300) \
            == _ref_tokens(params, cfg, rep, 8)
        assert greedy2.result(timeout=300) \
            == _ref_tokens(params, cfg, list(reversed(rep)), 8)
        out = sampled.result(timeout=300)
        assert len(out) == 8
        assert sampled.spec_drafted == 0     # sampled rows never draft
        _assert_no_block_leak(eng.stats())
    finally:
        eng.shutdown()


# ------------------------------------------------- metrics + timeline


def test_spec_metrics_and_per_request_accounting(params, cfg):
    """stats()/metrics_snapshot expose accept-rate and per-row
    tokens-per-step; each request carries its own accept accounting."""
    eng = InferenceEngine(params, cfg, _spec_cfg("ngram"))
    try:
        rep = [1, 2, 3, 4] * 6
        req = eng.submit(rep, max_new=8)
        assert req.result(timeout=300) == _ref_tokens(params, cfg, rep, 8)
        assert req.spec_drafted > 0
        assert req.spec_accepted > 0
        assert len(req.token_times) == 8     # per-token stamps = ITL series
        st = eng.stats()
        assert st["spec_accept_rate"] > 0.0
        assert st["tokens_per_step"] > 1.0
        series = {name: values for name, _, _, values in
                  metrics_snapshot()}
        assert "ray_tpu_inference_spec_accept_rate" in series
        assert "ray_tpu_inference_spec_accepted_tokens_total" in series
        assert "ray_tpu_inference_tokens_per_step" in series
        key = (("engine", eng.name),)
        assert series["ray_tpu_inference_spec_accept_rate"][key] > 0.0
        assert series["ray_tpu_inference_tokens_per_step"][key] > 1.0
    finally:
        eng.shutdown()


def test_timeline_renders_engine_request_slices():
    """engine_request flight-recorder events (engine._fr_note) become X
    slices on the engine's track with accept/reject counts in args."""
    from ray_tpu.util.timeline import build_trace
    trace = build_trace(ingress=[
        {"t": 20.5, "kind": "engine_request", "engine": "engine-0",
         "req": 3, "start_t": 20.0, "tokens": 8,
         "spec_accepted": 5, "spec_rejected": 2},
    ])
    sl = [e for e in trace["traceEvents"] if e.get("cat") == "engine"]
    assert len(sl) == 1 and sl[0]["ph"] == "X"
    assert sl[0]["pid"] == "engine" and sl[0]["tid"] == "engine-0"
    assert sl[0]["dur"] == pytest.approx(0.5e6)
    assert sl[0]["args"]["spec_accepted"] == 5
    assert sl[0]["args"]["spec_rejected"] == 2


def test_engine_emits_request_slice_to_flight_recorder(params, cfg):
    """With the flight recorder armed, every completed request lands an
    engine_request event carrying its speculation counts."""
    from ray_tpu.core import flight_recorder as fr

    rec = fr.enable()
    eng = InferenceEngine(params, cfg, _spec_cfg("ngram"))
    try:
        rep = [1, 2, 3, 4] * 6
        eng.generate(rep, max_new=6, timeout=300)
        evs = [e for e in rec.export_ingress()
               if e.get("kind") == "engine_request"]
        assert evs, "no engine_request event recorded"
        ev = evs[-1]
        assert ev["engine"] == eng.name
        assert ev["tokens"] == 6
        assert ev["spec_accepted"] >= 0 and ev["spec_rejected"] >= 0
        assert ev["spec_accepted"] + ev["spec_rejected"] > 0
        assert ev["t"] >= ev["start_t"]
    finally:
        eng.shutdown()
        fr.disable()
