"""Graceful node decommission (ISSUE 14 tentpole b): planned removal of
a cluster node goes ACTIVE -> DRAINING -> TERMINATED — new placement
stops, queued specs re-park to the head, running tasks finish under the
deadline, and owned-object primary copies / ownership records migrate
to a survivor — so reads after the exit need NO lineage re-execution
(handoff, not reconstruction) and nothing masquerades as failure."""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def cluster():
    c = Cluster()
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def _total_recons(nodes) -> int:
    return sum(lin["recons"] for n in nodes
               for lin in n.lineage.values())


def _wait_for(cond, timeout=30.0, what="condition"):
    """Event-polled wait (deflake: fixed sleeps raced the scheduler on
    loaded CI machines — poll the actual observable instead)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.01)
    pytest.fail(f"timed out waiting for {what}")


def test_node_decommission_e2e_8_nodes(cluster):
    """The acceptance e2e: drain one member of an 8-node cluster while
    it holds queued work, the only copy of a task result, AND a
    lineage-less ray.put object it OWNS.  Everything completes, both
    objects stay readable after the exit, and zero reconstructions ran
    — the handoff did the work, not the failure path."""
    n0 = cluster.add_node(num_cpus=2)
    pool = [cluster.add_node(num_cpus=1, resources={"pool": 2})
            for _ in range(6)]
    victim = cluster.add_node(num_cpus=1,
                              resources={"pool": 2, "vic": 4})
    cluster.wait_for_nodes()
    ray_tpu.init(address=n0.address)
    all_nodes = [n0, victim] + pool

    @ray_tpu.remote(resources={"vic": 1})
    def produce():
        # shm-sized: the only copy lives on the victim, owned by n0
        return np.arange(200_000, dtype=np.int64)

    @ray_tpu.remote(resources={"vic": 1})
    def put_inner():
        # ray.put inside a victim-hosted task: the OBJECT is owned by
        # the victim's node and has NO lineage — without the ownership
        # handoff this ref would die with the node (ObjectLostError)
        import ray_tpu as rt
        return rt.put(np.arange(50_000, dtype=np.int64))

    @ray_tpu.remote(resources={"pool": 1})
    def work(i):
        time.sleep(0.3)
        return i

    big_ref = produce.remote()
    inner_ref = ray_tpu.get(put_inner.remote(), timeout=120)

    # wait until the victim-held result settled at its owner (so the
    # drain exercises the HANDOFF, not in-flight forwarding)
    ob = big_ref.id.binary()
    deadline = time.time() + 60
    while time.time() < deadline:
        orec = n0.owned.get(ob)
        if orec is not None and orec.locations \
                and ob not in n0._fwd_by_oid:
            break
        time.sleep(0.05)
    else:
        pytest.fail("producer never settled at the owner")

    # mid-drain load: more pool tasks than instantaneous capacity, so
    # some are QUEUED on the victim when the drain begins — wait for
    # work to actually LAND there (queued or running), not a fixed
    # sleep that races the scheduler on loaded machines
    refs = [work.remote(i) for i in range(30)]
    _wait_for(lambda: (victim.runnable_cpu or victim.runnable_zero
                       or any(rec.current_task is not None
                              for rec in victim.clients.values())),
              what="pool work to land on the victim")
    res = ray_tpu.drain_node(victim.node_id.hex(), deadline_s=30)
    assert res.get("draining")

    # every queued/running task completes — re-parked, not killed
    out = ray_tpu.get(refs, timeout=180)
    assert sorted(out) == list(range(30))

    cluster.wait_node_gone(victim, timeout=60)
    head_rec = cluster.head.nodes[victim.node_id.hex()]
    # membership retired as a PLANNED removal, not a detected failure
    assert not head_rec.alive
    assert "decommissioned" in head_rec.death_cause

    # both objects readable after the exit, WITHOUT reconstruction
    big = ray_tpu.get(big_ref, timeout=120)
    inner = ray_tpu.get(inner_ref, timeout=120)
    assert big.shape == (200_000,) and big[123] == 123
    assert inner.shape == (50_000,) and inner[7] == 7
    assert _total_recons([n for n in all_nodes if n is not victim]) \
        == 0, "decommission must hand off, never reconstruct"

    # and the cluster keeps serving on the survivors
    assert ray_tpu.get(work.remote(99), timeout=120) == 99


def test_draining_node_takes_no_new_placements(cluster):
    """The head stops choosing a DRAINING node the moment the drain
    begins — tasks submitted during the drain land on survivors."""
    n0 = cluster.add_node(num_cpus=2)
    a = cluster.add_node(num_cpus=2, resources={"tag": 8})
    b = cluster.add_node(num_cpus=2, resources={"tag": 8})
    cluster.wait_for_nodes()
    ray_tpu.init(address=n0.address)

    @ray_tpu.remote(resources={"tag": 1})
    def where():
        from ray_tpu.core.runtime import get_runtime
        return get_runtime().client.node_id

    ray_tpu.drain_node(a.node_id.hex(), deadline_s=30)
    # draining flag lands on the head synchronously with the reply; all
    # subsequent placements must avoid node a
    homes = ray_tpu.get([where.remote() for _ in range(8)], timeout=120)
    assert set(homes) == {b.node_id.hex()}
    cluster.wait_node_gone(a, timeout=60)
    # view no longer carries the drained node
    alive = [n for n in ray_tpu.nodes() if n.get("alive")]
    assert a.node_id.hex() not in {n["node_id"] for n in alive}


def test_drain_waits_for_queued_actor_calls(cluster):
    """An actor can't move, so its QUEUED method calls must drain on
    the node before it exits — not just the call currently running
    (regression: _drain_busy once consulted only in-flight work, so a
    drain could exit between a call finishing and the next being
    dispatched, dropping the queue)."""
    n0 = cluster.add_node(num_cpus=2)
    victim = cluster.add_node(num_cpus=2, resources={"vic": 2})
    cluster.add_node(num_cpus=2)
    cluster.wait_for_nodes()
    ray_tpu.init(address=n0.address)

    @ray_tpu.remote(resources={"vic": 1})
    class Slow:
        def step(self, i):
            time.sleep(0.3)
            return i

    a = Slow.remote()
    # the actor must be LIVE before queueing (creation itself also
    # holds a drain open, but here the queue is the point)
    assert ray_tpu.get(a.step.remote(-1), timeout=120) == -1
    refs = [a.step.remote(i) for i in range(5)]   # 1 running + 4 queued
    # the regression is about the QUEUE: wait until calls are actually
    # parked on the victim's actor record before draining
    _wait_for(lambda: any(ar.queue for ar in victim.actors.values()),
              what="actor calls to queue on the victim")
    ray_tpu.drain_node(victim.node_id.hex(), deadline_s=30)
    assert ray_tpu.get(refs, timeout=120) == list(range(5))
    cluster.wait_node_gone(victim, timeout=60)


def test_drain_unknown_node_errors(cluster):
    n0 = cluster.add_node(num_cpus=1)
    cluster.wait_for_nodes()
    ray_tpu.init(address=n0.address)
    with pytest.raises(Exception, match="no alive node"):
        ray_tpu.drain_node("f" * 32, deadline_s=5)
