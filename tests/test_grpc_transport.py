"""Control-plane gRPC hosting (RAY_TPU_RPC=grpc).

Reference: src/ray/rpc/grpc_server.h — every control-plane service is
gRPC-hosted.  Here the framed message stream (typed proto payloads on
remote links) rides a gRPC bidi method; these tests run the real
cluster workloads over it in subprocesses so the env var applies from
process start.
"""

from __future__ import annotations

import os
import subprocess
import sys

import pytest

pytest.importorskip("grpc")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(code: str, timeout: float = 240.0) -> str:
    env = dict(os.environ)
    env["RAY_TPU_RPC"] = "grpc"
    env["PYTHONPATH"] = REPO
    proc = subprocess.run([sys.executable, "-u", "-c", code], env=env,
                          capture_output=True, text=True, timeout=timeout,
                          cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-3000:]
    return proc.stdout


def test_single_node_over_grpc():
    out = _run("""
import ray_tpu
ray_tpu.init(num_cpus=2, num_tpus=0)

@ray_tpu.remote
def sq(x): return x * x

print(sorted(ray_tpu.get([sq.remote(i) for i in range(5)], timeout=90)))

@ray_tpu.remote
class Counter:
    def __init__(self): self.n = 0
    def bump(self): self.n += 1; return self.n

c = Counter.remote()
print([ray_tpu.get(c.bump.remote(), timeout=60) for _ in range(3)])
ray_tpu.shutdown()
print("OK")
""")
    assert "[0, 1, 4, 9, 16]" in out
    assert "[1, 2, 3]" in out
    assert "OK" in out


def test_cluster_over_grpc():
    """Multi-node: head + 2 nodes, cross-node task routing, KV through
    the head proxy, cross-node object pull — all links on gRPC."""
    out = _run("""
import numpy as np
import ray_tpu
from ray_tpu.cluster_utils import Cluster

c = Cluster()
n0 = c.add_node(num_cpus=1)
c.add_node(num_cpus=1, resources={"tagged": 1})
c.wait_for_nodes()
ray_tpu.init(address=n0.address)

@ray_tpu.remote(resources={"tagged": 1})
def far(x):
    return x + 1

print("routed:", ray_tpu.get(far.remote(41), timeout=120))

rt = ray_tpu.get_runtime()
rt.client.kv_put(b"k", b"v")

@ray_tpu.remote(resources={"tagged": 1})
def read_kv():
    from ray_tpu.core.runtime import get_runtime
    return get_runtime().client.kv_get(b"k")

print("kv:", ray_tpu.get(read_kv.remote(), timeout=120))

@ray_tpu.remote(resources={"tagged": 1})
def big():
    return np.ones(300_000)

print("pull:", float(ray_tpu.get(big.remote(), timeout=120).sum()))
ray_tpu.shutdown()
c.shutdown()
print("OK")
""", timeout=420.0)
    assert "routed: 42" in out
    assert "kv: b'v'" in out
    assert "pull: 300000.0" in out
    assert "OK" in out
