"""Acceptance e2e for the elastic data plane (ISSUE 19): a streamed
shuffle-between-maps pipeline feeds a 4-member elastic trainer; the gang
is SIGKILL-shrunk 4->3 mid-epoch and re-grown 3->4 within the same
epoch; the merged sample ledger proves zero dropped / zero double-fed
samples, and the trained weight matches an undisturbed single-process
replay of the same spooled epoch bit-for-bit (loss parity by
construction: every step's update uses the step's GLOBAL batch, which
the pure-function sharding makes world-size invariant).

All coordination is scripted/event-driven — ledger files as progress
markers, an exclusive marker file for the exactly-once mid-epoch fault,
per-step partial files as the cross-rank reduce — no wall-clock races.
"""

from __future__ import annotations

import os
import signal
import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.data import Dataset
from ray_tpu.train.ingest import (DatasetShard, SampleLedger, merge_ledgers,
                                  validate_ledger)

pytestmark = [pytest.mark.slow, pytest.mark.chaos]

GLOBAL_BATCH = 16
NUM_ROWS = 256          # 16 full steps of 16
FAULT_STEP = 9          # scripted regrow-boundary fault (attempt 1)


@pytest.fixture
def rt():
    ray_tpu.init(num_cpus=8, num_tpus=0)
    yield ray_tpu
    ray_tpu.shutdown()


def _pipeline():
    """map -> streaming shuffle -> map: the shuffle runs INSIDE the
    operator graph when the driver spools the epoch."""
    return (Dataset.range(NUM_ROWS, parallelism=8)
            .map_batches(lambda b: {"x": b["id"] * 3.0})
            .streaming_shuffle(num_partitions=4, seed=5)
            .map_batches(lambda b: {"x": b["x"] + 1.0}))


def _loop(cfg):
    """SPMD member loop: per-step file-based allreduce of the sharded
    batch (partials on shared storage double as a step barrier), a
    deterministic weight update from the GLOBAL batch mean, periodic
    checkpoints, and one exclusive-marker scripted fault."""
    import json as _json
    import os as _os
    import time as _time

    import numpy as _np

    from ray_tpu.train import session

    shard = session.get_dataset_shard("train")
    assert shard is not None, "trainer did not wire the dataset shard"
    rank, world, attempt = shard.rank, shard.world, shard.attempt
    sync = cfg["sync_dir"]
    _os.makedirs(sync, exist_ok=True)

    def write_atomic(path, payload):
        tmp = path + f".tmp{rank}"
        with open(tmp, "w") as f:
            _json.dump(payload, f)
        _os.replace(tmp, path)

    w, start = 0.0, 0
    ck = session.get_checkpoint()
    if ck is not None:
        d = ck.to_dict()
        w, start = float(d["w"]), int(d["step"]) + 1
    last = shard.total_steps - 1
    for step, batch in shard.iter_batches(start_step=start):
        write_atomic(
            _os.path.join(sync, f"part-a{attempt}-s{step}-r{rank}.json"),
            {"s": float(_np.sum(batch["x"])), "n": int(len(batch["x"]))})
        # barrier-by-reduction: every rank's partial must land before
        # anyone steps — a dead peer stalls the world inside ONE step
        parts, deadline = None, _time.time() + 15
        while _time.time() < deadline:
            try:
                parts = []
                for r in range(world):
                    with open(_os.path.join(
                            sync,
                            f"part-a{attempt}-s{step}-r{r}.json")) as f:
                        parts.append(_json.load(f))
                break
            except (FileNotFoundError, ValueError):
                parts = None
                _time.sleep(0.01)
        if parts is None:
            raise RuntimeError(
                f"rank {rank}: step {step} reduce barrier timed out "
                f"(a peer died mid-step)")
        gsum = sum(p["s"] for p in parts)
        gn = sum(p["n"] for p in parts)
        assert gn == cfg["global_batch"], (gn, step)
        w = w + 0.001 * (gsum / gn)
        if rank == 0 and step == cfg["fault_step"]:
            try:
                fd = _os.open(cfg["marker"],
                              _os.O_CREAT | _os.O_EXCL | _os.O_WRONLY)
                _os.close(fd)
                raise RuntimeError("scripted regrow-boundary fault")
            except FileExistsError:
                pass             # second visit: the fault fired already
        _time.sleep(0.05)        # pace the epoch so the kill lands mid-epoch
        if step % 2 == 1 or step == last:
            session.report({"step": step, "w": w},
                           checkpoint={"w": w, "step": step})


def _watch_ledger_step(path, step, timeout=120):
    """Event-driven progress marker: block until the rank's ledger file
    shows a delivery at >= step."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            if SampleLedger.load(path).max_step() >= step:
                return
        except (FileNotFoundError, ValueError):
            pass
        time.sleep(0.02)
    pytest.fail(f"ledger {os.path.basename(path)} never reached "
                f"step {step}")


def test_streamed_shuffle_elastic_shrink_and_regrow(rt, tmp_path):
    from ray_tpu.train import DataParallelTrainer
    from ray_tpu.train.config import (FailureConfig, RunConfig,
                                      ScalingConfig)

    sync_dir = str(tmp_path / "sync")
    marker = str(tmp_path / "fault.marker")
    trainer = DataParallelTrainer(
        _loop,
        train_loop_config={"sync_dir": sync_dir, "marker": marker,
                           "global_batch": GLOBAL_BATCH,
                           "fault_step": FAULT_STEP},
        datasets={"train": _pipeline()},
        dataset_config={"global_batch_size": GLOBAL_BATCH, "epochs": 1},
        scaling_config=ScalingConfig(mesh={"dp": -1}, num_hosts=4,
                                     use_cpu_devices=True,
                                     devices_per_host=1, elastic=True),
        run_config=RunConfig(name="elastic_data", storage_path=str(tmp_path),
                             failure_config=FailureConfig(max_failures=3)))

    gang = trainer.gang
    pids = gang.member_pids()
    assert len(set(pids)) == 4

    holder: dict = {}

    def run_fit():
        try:
            holder["result"] = trainer.fit()
        except Exception as e:           # pragma: no cover - surfaced below
            holder["error"] = e

    t = threading.Thread(target=run_fit)
    t.start()

    run_dir = os.path.join(str(tmp_path), "elastic_data")
    ledger_dir = os.path.join(run_dir, "ingest", "train", "ledger")
    # shrink 4->3: kill rank 1 once ITS ledger proves it is mid-epoch
    _watch_ledger_step(
        os.path.join(ledger_dir, "train-rank1-attempt0.json"), 5)
    os.kill(pids[1], signal.SIGKILL)
    # regrow 3->4 happens at the next re-gang boundary, forced by the
    # marker-guarded fault at FAULT_STEP inside attempt 1 (world 3)

    t.join(timeout=600)
    assert not t.is_alive(), "fit() hung across the resize sequence"
    assert "error" not in holder, holder.get("error")
    result = holder["result"]
    assert result.error is None
    assert result.metrics["step"] == NUM_ROWS // GLOBAL_BATCH - 1

    # the gang went 4 -> 3 -> 4 and ended at the target world
    assert trainer.gang.num_members == 4
    assert os.path.exists(marker), "the scripted regrow fault never fired"

    # --- exactly-once proof ------------------------------------------------
    steps = NUM_ROWS // GLOBAL_BATCH
    merged = merge_ledgers(ledger_dir)
    audit = validate_ledger(merged, steps, GLOBAL_BATCH)
    assert audit["ok"], audit

    # the resize history is visible in the ledger: 4 shards delivered at
    # attempt 0, 3 at the shrunk attempt 1, 4 again after readmission
    worlds = {}
    for e in merged.entries:
        worlds.setdefault(e.attempt, set()).add(e.shard)
    assert len(worlds[0]) == 4, worlds
    assert len(worlds[1]) == 3, worlds
    assert len(worlds[2]) == 4, worlds

    # --- loss parity with an undisturbed run -------------------------------
    # replay the SAME spooled epoch single-process: every step's update
    # used the global batch mean, so the resize history cannot change w
    manifest = os.path.join(run_dir, "ingest", "train", "manifest.json")
    ref = DatasetShard(manifest, rank=0, world=1,
                       global_batch=GLOBAL_BATCH,
                       ledger_dir=str(tmp_path / "replay_ledger"),
                       name="replay")
    w_ref = 0.0
    for _step, batch in ref.iter_batches():
        w_ref += 0.001 * (float(np.sum(batch["x"])) / GLOBAL_BATCH)
    w_final = result.checkpoint.to_dict()
    assert np.isclose(float(w_final["w"]), w_ref, rtol=0, atol=1e-9), \
        (float(w_final["w"]), w_ref)
    # and the spool itself respected the shuffle: a permutation of the
    # mapped rows, not the identity order
    spooled = np.concatenate(
        [ref.read_rows(s * GLOBAL_BATCH, (s + 1) * GLOBAL_BATCH)["x"]
         for s in range(steps)])
    expect = np.arange(NUM_ROWS) * 3.0 + 1.0
    assert sorted(spooled.tolist()) == sorted(expect.tolist())
    assert not np.array_equal(spooled, expect)
