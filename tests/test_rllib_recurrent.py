"""R2D2 + QMIX tests (reference test models:
rllib/algorithms/r2d2/tests/, rllib/algorithms/qmix/tests/)."""

import numpy as np
import pytest

from ray_tpu.rllib.qmix import QMIXConfig, TeamSwitch
from ray_tpu.rllib.r2d2 import R2D2Config, _h, _h_inv


class TestR2D2:
    def test_value_rescaling_inverse(self):
        import jax.numpy as jnp
        x = jnp.asarray([-10.0, -1.0, 0.0, 0.5, 7.0, 100.0])
        np.testing.assert_allclose(np.asarray(_h_inv(_h(x))),
                                   np.asarray(x), rtol=1e-4, atol=1e-4)

    def test_trains_and_loss_drops(self):
        algo = R2D2Config(env="CartPole-v1", num_envs_per_worker=2,
                          rollout_length=64, learning_starts=8,
                          batch_size=8, seq_len=8, burn_in=2,
                          seed=0).build()
        losses = [algo.train()["mean_td_loss"] for _ in range(3)]
        assert all(np.isfinite(l) for l in losses)
        assert losses[-1] < losses[0]

    def test_sequences_carry_stored_state(self):
        algo = R2D2Config(env="CartPole-v1", num_envs_per_worker=1,
                          rollout_length=40, learning_starts=10_000,
                          seq_len=8, burn_in=2, seed=0).build()
        algo.train()
        assert len(algo.buffer) >= 4
        row = algo.buffer.rows[-1]
        # obs includes the bootstrap successor; h0/c0 stored per sequence
        assert row["obs"].shape[0] == 8 + 1
        assert row["h0"].shape == (algo.config.cell_size,)

    def test_checkpoint_roundtrip(self):
        import jax
        algo = R2D2Config(env="CartPole-v1", num_envs_per_worker=1,
                          rollout_length=16, learning_starts=4,
                          batch_size=4, seq_len=4, burn_in=1,
                          seed=0).build()
        algo.train()
        ck = algo.save_checkpoint()
        before = jax.tree.map(np.asarray, algo.params)
        algo.train()
        algo.load_checkpoint(ck)
        after = jax.tree.map(np.asarray, algo.params)
        for a, b in zip(jax.tree.leaves(before), jax.tree.leaves(after)):
            np.testing.assert_allclose(a, b)


class TestQMIX:
    def test_team_switch_env_contract(self):
        env = TeamSwitch(num_agents=3, seed=0)
        obs = env.reset()
        assert set(obs) == {"agent_0", "agent_1", "agent_2"}
        assert env.state().shape == (4,)
        o, r, d, _ = env.step({a: 0 for a in env.agent_ids})
        assert set(r.values()) <= {0.0, 1.0}
        assert "__all__" in d

    def test_mixer_monotonic_in_agent_q(self):
        import jax
        import jax.numpy as jnp

        from ray_tpu.rllib.qmix import init_qmix_params, mix
        params = init_qmix_params(2, 2, 2, (32, 32), 3, 16,
                                  jax.random.PRNGKey(0))
        state = jnp.ones((1, 3))
        q1 = float(mix(params, jnp.asarray([[0.0, 0.0]]), state)[0])
        q2 = float(mix(params, jnp.asarray([[1.0, 0.0]]), state)[0])
        q3 = float(mix(params, jnp.asarray([[1.0, 1.0]]), state)[0])
        # |W| hypernetworks guarantee dQtot/dQa >= 0
        assert q2 >= q1 and q3 >= q2

    @pytest.mark.slow
    def test_qmix_learns_team_switch(self):
        algo = QMIXConfig(num_agents=2, rollout_length=256,
                          learning_starts=100, batch_size=32,
                          epsilon_decay_steps=2000, seed=0).build()
        for _ in range(10):
            algo.train()
        # random play scores ~2/8; optimum is 8.0 (each agent plays its
        # own observed bit every step)
        recent = float(np.mean(algo._ep_returns[-50:]))
        assert recent > 6.0, f"QMIX stuck at {recent}"
