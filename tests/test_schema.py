"""Wire-schema conformance: real control-plane traffic shapes must
round-trip through the protobuf contract (reference analogue: the
.proto files under src/ray/protobuf/ ARE the contract; here CI proves
dict ⇄ proto fidelity so the encoding can flip without caller churn)."""

import numpy as np
import pytest

from ray_tpu.core import schema


def roundtrip(m):
    return schema.decode(schema.encode(m))


class TestTaskSpec:
    def test_plain_task_spec(self):
        spec = {
            "task_id": b"T" * 24, "kind": "task", "name": "f",
            "function_id": "abc123", "num_returns": 2,
            "return_ids": [b"R1" + b"\0" * 26, b"R2" + b"\0" * 26],
            "resources": {"CPU": 1.0}, "num_tpus": 0.0,
            "max_retries": 3, "owner": "driver-1",
            "args": b"SERIALIZED-TUPLE",
            "arg_ids": [b"O" * 28],
            "placement_group": (b"P" * 16, 1),
        }
        out = roundtrip({"t": "submit_task", "spec": spec, "reqid": 7})
        assert out["t"] == "submit_task" and out["reqid"] == 7
        s = out["spec"]
        assert s["task_id"] == spec["task_id"]
        assert s["num_returns"] == 2
        assert s["resources"] == {"CPU": 1.0}
        assert s["args"] == b"SERIALIZED-TUPLE"
        assert s["arg_ids"] == [b"O" * 28]
        assert s["placement_group"] == (b"P" * 16, 1)

    def test_arg_blob_spill(self):
        spec = {"task_id": b"T" * 24, "kind": "task", "name": "f",
                "function_id": "x", "num_returns": 1,
                "return_ids": [b"R" * 28], "owner": "d",
                "args": b"", "arg_blob": b"B" * 28,
                "arg_ids": [b"B" * 28]}
        s = roundtrip({"t": "submit_task", "spec": spec})["spec"]
        assert s["arg_blob"] == b"B" * 28 and s["args"] == b""

    def test_dynamic_returns_and_trace(self):
        spec = {"task_id": b"T" * 24, "kind": "task", "name": "g",
                "function_id": "f1", "num_returns": "dynamic",
                "return_ids": [b"R" * 28], "owner": "d",
                "args": b"",
                "trace_ctx": {"trace_id": "t" * 32, "span_id": "s" * 16}}
        s = roundtrip({"t": "submit_task", "spec": spec})["spec"]
        assert s["num_returns"] == "dynamic"
        assert s["trace_ctx"]["trace_id"] == "t" * 32

    def test_actor_create_and_task(self):
        create = {"task_id": b"T" * 24, "kind": "actor_create",
                  "actor_id": b"A" * 16, "class_name": "Counter",
                  "methods": ["incr", "get"], "function_id": "cls1",
                  "num_returns": 0, "return_ids": [], "args": b"",
                  "max_restarts": 2, "max_concurrency": 4,
                  "namespace": "ns", "get_if_exists": True}
        out = roundtrip({"t": "create_actor", "spec": create})
        assert out["t"] == "create_actor"
        assert out["spec"]["methods"] == ["incr", "get"]
        assert out["spec"]["max_concurrency"] == 4

        call = {"task_id": b"T" * 24, "kind": "actor_task",
                "actor_id": b"A" * 16, "method": "incr", "seq": 9,
                "num_returns": 1, "return_ids": [b"R" * 28],
                "owner": "d", "args": b"x"}
        out = roundtrip({"t": "submit_actor_task", "spec": call})
        assert out["t"] == "submit_actor_task"
        assert out["spec"]["method"] == "incr"
        assert out["spec"]["seq"] == 9


class TestMessages:
    def test_objects_plane(self):
        m = roundtrip({"t": "put_inline", "object_id": b"O" * 28,
                       "data": b"\x80\x05bytes", "is_error": False,
                       "owner": "d", "nested_refs": [b"N" * 28]})
        assert m["t"] == "put_inline" and m["nested_refs"] == [b"N" * 28]

        m = roundtrip({"t": "get_objects",
                       "object_ids": [b"A" * 28, b"B" * 28]})
        assert m["object_ids"] == [b"A" * 28, b"B" * 28]

        m = roundtrip({"t": "wait", "object_ids": [b"A" * 28],
                       "num_returns": 1, "timeout": None})
        assert m["timeout"] is None
        m = roundtrip({"t": "wait", "object_ids": [b"A" * 28],
                       "num_returns": 1, "timeout": 2.5})
        assert m["timeout"] == 2.5

    def test_kv_and_pubsub(self):
        m = roundtrip({"t": "kv_put", "key": b"k", "value": b"v",
                       "overwrite": True, "namespace": "default"})
        assert m["key"] == b"k" and m["overwrite"] is True
        m = roundtrip({"t": "publish", "channel": "logs",
                       "data": {"line": "hello", "n": np.int64(3)}})
        assert m["data"]["line"] == "hello"

    def test_heartbeat(self):
        m = roundtrip({"t": "heartbeat", "node_id": "n1",
                       "available": {"CPU": 3.5}, "seq": 42})
        assert m["available"] == {"CPU": 3.5} and m["seq"] == 42

    def test_raw_fallback_long_tail(self):
        m = roundtrip({"t": "need_space", "nbytes": 1 << 20,
                       "reqid": 3})
        assert m["t"] == "need_space" and m["nbytes"] == 1 << 20

    def test_drain_protocol_messages_roundtrip(self):
        """The decommission wire vocabulary (drain_node / node_drain /
        drain_done / owner_handoff) rides the typed Raw envelope —
        pinned here so the shapes can't drift silently."""
        m = roundtrip({"t": "drain_node", "node_id": "ab" * 16,
                       "deadline_s": 12.5, "reqid": 7})
        assert m["t"] == "drain_node" and m["deadline_s"] == 12.5
        m = roundtrip({"t": "node_drain", "deadline_s": 30.0})
        assert m["t"] == "node_drain" and "reqid" not in m
        m = roundtrip({"t": "drain_done", "node_id": "cd" * 16,
                       "timed_out": False, "reqid": 9})
        assert m["t"] == "drain_done" and m["timed_out"] is False
        m = roundtrip({"t": "owner_handoff", "from_hex": "ef" * 16,
                       "from_addr": "127.0.0.1:1",
                       "objects": [{"object_id": b"\x01" * 20,
                                    "data": b"bytes", "is_error": False,
                                    "task_id": b"\x02" * 14,
                                    "locations": {"aa": "x:1"},
                                    "lineage": None}]})
        assert m["objects"][0]["data"] == b"bytes"
        assert m["objects"][0]["locations"] == {"aa": "x:1"}

    def test_prefix_plane_messages_roundtrip(self):
        """The cluster-prefix wire vocabulary (prefix_publish /
        prefix_lookup / prefix_invalidate / block_fetch) rides the
        typed Raw envelope — pinned here so the shapes can't drift
        silently (serve/fleet/prefix_directory.py speaks them, the
        head and node plane answer them)."""
        m = roundtrip({"t": "prefix_publish", "reqid": 3,
                       "keys": ["m|" + "a" * 32, "m|" + "b" * 32],
                       "holder": "v1#0", "n_tokens": 32,
                       "generation": 2, "block_size": 16,
                       "engine": "engine-7"})
        assert m["t"] == "prefix_publish" and m["generation"] == 2
        assert m["keys"][1].startswith("m|b") and m["block_size"] == 16
        m = roundtrip({"t": "prefix_lookup", "reqid": 4,
                       "keys": ["|" + "c" * 32]})
        assert m["t"] == "prefix_lookup" and len(m["keys"]) == 1
        m = roundtrip({"t": "prefix_invalidate", "reqid": 5,
                       "holder": "v1#0", "stale_generation": 1})
        assert m["t"] == "prefix_invalidate"
        assert m["stale_generation"] == 1
        m = roundtrip({"t": "block_fetch", "reqid": 6,
                       "engine": "engine-7",
                       "tokens": [1, 2, 3, 4], "generation": 0})
        assert m["t"] == "block_fetch" and m["tokens"] == [1, 2, 3, 4]

    def test_elastic_ingest_messages_roundtrip(self):
        """The elastic data plane's accounting vocabulary
        (sample_ledger / ingest_manifest) rides the typed Raw envelope
        — pinned here so the shapes can't drift silently
        (train/ingest.py writes them per step and per spool; the merge
        / validate audit path reads them back).  Ledger entries are
        positional 6-lists: [shard, step, start, stop, attempt,
        epoch]."""
        from ray_tpu.train.ingest import SampleLedger
        m = roundtrip({"t": "sample_ledger", "epoch": 0,
                       "entries": [[0, 3, 48, 56, 1, 0],
                                   [1, 3, 56, 64, 1, 0]]})
        assert m["t"] == "sample_ledger" and len(m["entries"]) == 2
        assert m["entries"][0] == [0, 3, 48, 56, 1, 0]
        led = SampleLedger.from_wire(m)   # codec output feeds the audit
        assert led.max_step() == 3 and len(led) == 2
        m = roundtrip({"t": "ingest_manifest", "epoch": 1,
                       "block_files": ["block-00000.npz"],
                       "row_offsets": [0, 128], "total_rows": 128,
                       "columns": ["x", "y"]})
        assert m["t"] == "ingest_manifest" and m["row_offsets"] == [0, 128]
        assert m["columns"] == ["x", "y"] and m["total_rows"] == 128

    def test_empty_oneof_arm_selected(self):
        # an all-defaults message must still carry its type
        m = roundtrip({"t": "get_objects", "object_ids": []})
        assert m["t"] == "get_objects" and m["object_ids"] == []


def test_encoding_is_compact_vs_pickle():
    import pickle
    m = {"t": "get_objects", "reqid": 5,
         "object_ids": [bytes([i] * 28) for i in range(20)]}
    assert len(schema.encode(m)) < len(pickle.dumps(m, protocol=5))
