"""TorchTrainer tests (reference test model:
python/ray/train/tests/test_torch_trainer.py — process-group formation,
allreduce correctness, DDP gradient sync, report/checkpoint flow)."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

from ray_tpu.train import (Checkpoint, RunConfig, ScalingConfig,  # noqa: E402
                           TorchConfig, TorchTrainer)


def _loop_allreduce(config):
    import torch
    import torch.distributed as dist

    from ray_tpu.train import session
    rank = session.get_world_rank()
    world = session.get_world_size()
    t = torch.tensor([float(rank + 1)])
    dist.all_reduce(t)
    # sum over ranks: 1 + 2 + ... + world
    session.report({"allreduce": float(t.item()),
                    "rank": rank, "world": world})


def test_process_group_allreduce(rt_init, tmp_path):
    trainer = TorchTrainer(
        _loop_allreduce,
        scaling_config=ScalingConfig(num_workers=2),
        torch_config=TorchConfig(backend="gloo"),
        run_config=RunConfig(storage_path=str(tmp_path)))
    result = trainer.fit()
    assert result.metrics["allreduce"] == 3.0   # 1 + 2
    assert result.metrics["world"] == 2


def _loop_ddp_train(config):
    import torch
    import torch.distributed as dist

    from ray_tpu.train import prepare_model, session
    torch.manual_seed(0)
    model = prepare_model(torch.nn.Linear(4, 1))
    opt = torch.optim.SGD(model.parameters(), lr=0.1)
    rank = session.get_world_rank()
    torch.manual_seed(100 + rank)   # different data per rank
    x = torch.randn(16, 4)
    y = x.sum(dim=1, keepdim=True)
    for step in range(config["steps"]):
        opt.zero_grad()
        loss = torch.nn.functional.mse_loss(model(x), y)
        loss.backward()             # DDP allreduces gradients
        opt.step()
        # weights must stay identical across ranks after DDP steps
        w = [p.detach().clone() for p in model.parameters()]
        flat = torch.cat([t.reshape(-1) for t in w])
        flat_max = flat.clone()
        dist.all_reduce(flat_max, op=dist.ReduceOp.MAX)
        flat_min = flat.clone()
        dist.all_reduce(flat_min, op=dist.ReduceOp.MIN)
        in_sync = bool(torch.allclose(flat_max, flat_min))
        session.report({"loss": float(loss.item()),
                        "weights_in_sync": in_sync},
                       checkpoint={"step": step,
                                   "flat": flat.numpy()})


def test_ddp_training_syncs_weights(rt_init, tmp_path):
    trainer = TorchTrainer(
        _loop_ddp_train, train_loop_config={"steps": 3},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path=str(tmp_path)))
    result = trainer.fit()
    assert result.metrics["weights_in_sync"] is True
    assert result.checkpoint is not None
    ck = result.checkpoint.to_dict()
    assert ck["step"] == 2 and ck["flat"].shape == (5,)


def _loop_resume(config):
    from ray_tpu.train import session
    ck = session.get_checkpoint()
    start = ck.to_dict()["i"] if ck is not None else 0
    for i in range(start, 3):
        session.report({"i": i}, checkpoint={"i": i + 1})


def test_resume_from_checkpoint(rt_init, tmp_path):
    sc = ScalingConfig(num_workers=1)
    r1 = TorchTrainer(
        _loop_resume, scaling_config=sc,
        run_config=RunConfig(storage_path=str(tmp_path))).fit()
    assert r1.metrics["i"] == 2
    # resume: starts from i=3 → no new work, single report loop done
    r2 = TorchTrainer(
        _loop_resume, scaling_config=sc,
        resume_from_checkpoint=Checkpoint.from_dict({"i": 2}),
        run_config=RunConfig(storage_path=str(tmp_path / "b"))).fit()
    assert r2.metrics["i"] == 2
