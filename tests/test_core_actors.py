"""Actor API tests (reference analogue: python/ray/tests/test_actor.py,
test_named_actors, actor restart paths of test_failure.py)."""

import time

import pytest

import ray_tpu


@pytest.fixture(scope="module")
def rt():
    ray_tpu.init(num_cpus=2, num_tpus=0)
    yield ray_tpu
    ray_tpu.shutdown()


@ray_tpu.remote
class Counter:
    def __init__(self, start=0):
        self.v = start

    def inc(self, n=1):
        self.v += n
        return self.v

    def value(self):
        return self.v

    def fail(self):
        raise RuntimeError("method error")

    def pid(self):
        import os
        return os.getpid()


def test_actor_basic(rt):
    c = Counter.remote(10)
    assert rt.get(c.inc.remote(), timeout=60) == 11
    assert rt.get(c.inc.remote(5), timeout=60) == 16
    assert rt.get(c.value.remote(), timeout=60) == 16


def test_actor_method_ordering(rt):
    c = Counter.remote(0)
    refs = [c.inc.remote() for _ in range(20)]
    # sequential queue: results must be 1..20 in submission order
    assert rt.get(refs, timeout=60) == list(range(1, 21))


def test_actor_method_error(rt):
    c = Counter.remote(0)
    with pytest.raises(ray_tpu.TaskError, match="method error"):
        rt.get(c.fail.remote(), timeout=60)
    # actor survives a method error
    assert rt.get(c.inc.remote(), timeout=60) == 1


def test_actor_state_isolated(rt):
    a = Counter.remote(0)
    b = Counter.remote(100)
    rt.get([a.inc.remote(), b.inc.remote()], timeout=60)
    assert rt.get(a.value.remote(), timeout=60) == 1
    assert rt.get(b.value.remote(), timeout=60) == 101


def test_named_actor(rt):
    Counter.options(name="named_cnt").remote(7)
    h = ray_tpu.get_actor("named_cnt")
    assert rt.get(h.value.remote(), timeout=60) == 7


def test_list_named_actors(rt):
    """The `list_named_actors` RPC existed on the head AND node since
    the named-actor PR but nothing ever sent it — `ray_tpu lint`'s
    protocol pass surfaced the dead handlers, and this public API
    (reference: ray.util.list_named_actors) is the fix."""
    h = Counter.options(name="lna_cnt").remote(1)
    rt.get(h.value.remote(), timeout=60)
    names = ray_tpu.list_named_actors()
    assert "lna_cnt" in names
    full = ray_tpu.list_named_actors(all_namespaces=True)
    assert {"namespace": "default", "name": "lna_cnt"} in full
    with pytest.raises(ValueError, match="conflicts"):
        ray_tpu.list_named_actors(all_namespaces=True, namespace="x")


def test_named_actor_duplicate_raises(rt):
    Counter.options(name="dup_cnt").remote(0)
    with pytest.raises(Exception, match="already taken"):
        Counter.options(name="dup_cnt").remote(0)


def test_get_if_exists(rt):
    a = Counter.options(name="gie_cnt").remote(5)
    rt.get(a.value.remote(), timeout=60)
    b = Counter.options(name="gie_cnt", get_if_exists=True).remote(99)
    assert rt.get(b.value.remote(), timeout=60) == 5


def test_get_missing_named_actor_raises(rt):
    with pytest.raises(Exception, match="not found"):
        ray_tpu.get_actor("does_not_exist")


def test_kill_actor(rt):
    c = Counter.remote(0)
    rt.get(c.inc.remote(), timeout=60)
    ray_tpu.kill(c)
    time.sleep(0.5)
    with pytest.raises(Exception):
        rt.get(c.value.remote(), timeout=20)


def test_actor_restart(rt):
    c = Counter.options(max_restarts=1).remote(0)
    old_pid = rt.get(c.pid.remote(), timeout=60)

    @ray_tpu.remote
    def noop():
        return 1

    import os
    import signal
    os.kill(old_pid, signal.SIGKILL)
    # state is lost but the actor comes back on a fresh worker
    deadline = time.time() + 60
    new_pid = None
    while time.time() < deadline:
        try:
            new_pid = rt.get(c.pid.remote(), timeout=30)
            break
        except Exception:
            time.sleep(0.5)
    assert new_pid is not None and new_pid != old_pid


def test_actor_handle_in_task(rt):
    c = Counter.remote(0)

    @ray_tpu.remote
    def bump(handle):
        return rt.get(handle.inc.remote())

    assert rt.get(bump.remote(c), timeout=60) == 1
    assert rt.get(c.value.remote(), timeout=60) == 1


def test_unknown_method_raises(rt):
    c = Counter.remote(0)
    with pytest.raises(AttributeError):
        c.nope.remote()
