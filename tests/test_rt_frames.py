"""Native dispatch-frame codec: parity, fallback, and ring tests.

The contract under test (ISSUE 12): the C codec in
``native/src/rt_frames.cc`` and the pure-Python reference in
``core/rt_frames.py`` produce BYTE-IDENTICAL frames for every eligible
message (flight-recorder stamps and chaos retry markers included), both
decoders accept both encoders' output, ineligible messages fall back to
pickle on both paths, and a missing ``.so`` leaves the whole dispatch
plane on the identical pre-existing pickle path.
"""

import math
import os
import random
import string
import struct
import subprocess
import sys
import time
import threading

import pytest

from ray_tpu.core import protocol
from ray_tpu.core import rt_frames as rtf
from ray_tpu.native import frames as native_frames

HAVE_NATIVE = native_frames.available()
needs_native = pytest.mark.skipif(
    not HAVE_NATIVE, reason="librt_frames.so not built (no compiler?)")


@pytest.fixture(scope="module")
def codec():
    if not HAVE_NATIVE:
        pytest.skip("librt_frames.so unavailable")
    return native_frames.NativeFrameCodec()


# -- fuzz generator ---------------------------------------------------------

_STR_POOL = string.printable + "é漢🎉 "


def _rand_value(rng, depth=0):
    kinds = ["none", "bool", "int", "float", "bytes", "str"]
    if depth < 4:
        kinds += ["list", "tuple", "dict"]
    k = rng.choice(kinds)
    if k == "none":
        return None
    if k == "bool":
        return rng.random() < 0.5
    if k == "int":
        return rng.randint(-2**63, 2**63 - 1)
    if k == "float":
        return rng.choice([0.0, -0.0, 1.5, -2.75, 1e-300, 1e300,
                           float("inf"), float("-inf"), rng.random()])
    if k == "bytes":
        return bytes(rng.randrange(256) for _ in range(rng.randrange(24)))
    if k == "str":
        return "".join(rng.choice(_STR_POOL)
                       for _ in range(rng.randrange(16)))
    if k == "list":
        return [_rand_value(rng, depth + 1) for _ in range(rng.randrange(4))]
    if k == "tuple":
        return tuple(_rand_value(rng, depth + 1)
                     for _ in range(rng.randrange(4)))
    return {(f"k{i}" if rng.random() < 0.7 else bytes([65 + i])):
            _rand_value(rng, depth + 1) for i in range(rng.randrange(4))}


def _rand_message(rng):
    msg = {f"k{i}": _rand_value(rng) for i in range(rng.randrange(6))}
    roll = rng.random()
    if roll < 0.4:
        # realistic lifecycle record: stamps from client + node + chaos
        # retry markers — exactly what rides spec/result frames
        msg["fr"] = [("submit", 1.25), ("encode", 2.5),
                     ("node_recv", 3.0), ("retry", 4.75)]
    elif roll < 0.6:
        msg["fr"] = _rand_value(rng)   # non-list "fr": never stamped
    return msg


# -- parity -----------------------------------------------------------------

@needs_native
def test_fuzz_encode_parity(codec):
    """5k random messages: native and Python encoders agree byte-for-
    byte (stamped and unstamped), both decoders invert both, and the C
    validator accepts every produced frame."""
    rng = random.Random(0xC0DEC)
    checked = 0
    for _ in range(5000):
        msg = _rand_message(rng)
        stamp = rng.choice([None, "dispatch", "node_recv"])
        py = rtf.py_encode_frame(msg, stamp=stamp, now=42.125)
        nat = codec.encode_frame(msg, stamp=stamp, now=42.125)
        assert (py is None) == (nat is None), msg
        if py is None:
            continue
        checked += 1
        assert py == nat, (msg, py.hex(), nat.hex())
        payload = py[8:]
        (n,) = struct.unpack_from("<Q", py)
        assert n == len(payload)
        assert codec.validate(payload) == 0
        d_py = rtf.py_decode_payload(payload)
        d_nat = codec.decode_payload(payload)
        assert d_py == d_nat
        assert protocol.decode_payload(payload) == d_nat
    assert checked > 3000   # the generator mostly produces eligible msgs


@needs_native
def test_stamp_fold_appends_to_first_fr_list(codec):
    spec = {"fr": [("submit", 1.0)], "task_id": b"\x01" * 22}
    msg = {"t": "execute", "spec": spec}
    frame = codec.encode_frame(msg, stamp="dispatch", now=9.5)
    out = codec.decode_payload(frame[8:])
    assert out["spec"]["fr"] == [("submit", 1.0), ("dispatch", 9.5)]
    # the caller's dict was NOT mutated — the fold is frame-only
    assert spec["fr"] == [("submit", 1.0)]
    # pure-Python reference behaves identically
    assert rtf.py_encode_frame(msg, stamp="dispatch", now=9.5) == frame
    # live clock: a real stamp is monotonic-now, strictly positive
    live = codec.decode_payload(
        codec.encode_frame(msg, stamp="dispatch")[8:])
    assert live["spec"]["fr"][-1][0] == "dispatch"
    assert live["spec"]["fr"][-1][1] > 0.0


@needs_native
def test_py_stamp_matches_encoder_fold(codec):
    """The pickle-fallback stamp (py_stamp) must land on the SAME "fr"
    list the encoders' in-frame fold would pick — a native-armed peer
    and a fallback peer stamping the same message shape must produce
    the same flight-recorder timeline.  Shapes from the review that
    the old BFS-over-dicts py_stamp got wrong: fr nested inside a
    list, and a deeper fr occurring before a shallower one in
    pre-order."""
    shapes = [
        {"t": "execute", "spec": {"fr": [("a", 1.0)], "x": 1}},
        {"t": "task_done", "fr": [("a", 1.0)]},
        {"t": "batch", "specs": [{"fr": [("a", 1.0)]}], "fr": [("b", 2.0)]},
        {"a": {"fr": [("x", 1.0)]}, "fr": [("y", 2.0)]},
        {"a": [({"fr": [("x", 1.0)]},)], "fr": [("y", 2.0)]},
        {"fr": "not-a-list", "spec": {"fr": [("a", 1.0)]}},
    ]
    import copy
    for msg in shapes:
        folded = codec.decode_payload(
            codec.encode_frame(msg, stamp="S", now=7.5)[8:])
        stamped = copy.deepcopy(msg)
        rtf.py_stamp(stamped, "S", now=7.5)
        assert stamped == folded, (msg, stamped, folded)


@needs_native
def test_nan_and_utf8_parity(codec):
    nan_frame_py = rtf.py_encode_frame({"x": float("nan")})
    nan_frame_nat = codec.encode_frame({"x": float("nan")})
    assert nan_frame_py == nan_frame_nat
    out = codec.decode_payload(nan_frame_nat[8:])
    assert math.isnan(out["x"])
    s = "héllo 漢字 🎉 \x00 end"
    f = codec.encode_frame({"s": s})
    assert f == rtf.py_encode_frame({"s": s})
    assert codec.decode_payload(f[8:])["s"] == s


@needs_native
def test_ineligible_messages_fall_back_identically(codec):
    class DictSub(dict):
        pass

    for bad in ({"x": object()}, {"x": 2**70}, {"x": -2**70},
                {"x": DictSub(a=1)}, {1: "int key"}, {"x": {1: 2}},
                {"x": {2.5: "float key"}}, {"x": set([1])},
                {"x": bytearray(b"ba")}, {"x": [1, (2, {"y": object()})]}):
        assert rtf.py_encode_frame(bad) is None, bad
        assert codec.encode_frame(bad) is None, bad
    # nesting past MAX_DEPTH is ineligible, not a crash
    deep = cur = {}
    for _ in range(rtf.MAX_DEPTH + 2):
        cur["d"] = {}
        cur = cur["d"]
    assert rtf.py_encode_frame(deep) is None
    assert codec.encode_frame(deep) is None
    # ...and the wire path still delivers them via pickle
    data = protocol.encode_payload({"x": {1: 2}})
    assert data[:1] == protocol._TAG_PICKLE
    assert protocol.decode_payload(data) == {"x": {1: 2}}


@needs_native
def test_malformed_frames_rejected_not_crashed(codec):
    good = codec.encode_frame({"t": "ping", "n": 7, "b": b"xy"})[8:]
    # every truncation raises on both decoders (and fails validation)
    for cut in range(len(good)):
        bad = good[:cut]
        assert codec.validate(bad) != 0
        with pytest.raises(ValueError):
            codec.decode_payload(bad)
        with pytest.raises(ValueError):
            rtf.py_decode_payload(bad)
    # corrupted value tag
    bad = good[:1] + b"\x7f" + good[2:]
    with pytest.raises(ValueError):
        codec.decode_payload(bad)
    with pytest.raises(ValueError):
        rtf.py_decode_payload(bad)
    # non-map top level
    with pytest.raises(ValueError):
        rtf.py_decode_payload(b"\x03N")
    with pytest.raises(ValueError):
        codec.decode_payload(b"\x03N")


@needs_native
def test_cross_decoder_interop(codec):
    """A native-armed peer must interoperate with a fallback peer: the
    pure-Python decoder reads native frames even when this process's
    codec is disarmed (protocol.decode_payload's fallback arm)."""
    msg = {"t": "task_done", "task_id": b"\x02" * 22, "error": None,
           "fr": [("submit", 1.0), ("done", 2.0)]}
    frame = codec.encode_frame(msg)[8:]
    saved = rtf._active
    rtf.disable()
    try:
        assert protocol.decode_payload(frame) == msg
    finally:
        rtf._active = saved


# -- arming / fallback ------------------------------------------------------

def test_missing_so_leaves_codec_disarmed(monkeypatch):
    """The exact .so-absent path: the loader pointed at a nonexistent
    library must leave ``_active`` None (pickle path) rather than
    raise."""
    monkeypatch.setenv("RAY_TPU_FRAMES_LIB", "/nonexistent/librt.so")
    monkeypatch.setattr(native_frames, "_libs", None)
    monkeypatch.setattr(rtf, "_active", None)
    assert not rtf.enable()
    assert rtf._active is None
    # dumps_frame on the disarmed path is the pre-existing pickle frame
    f = protocol.dumps_frame({"t": "ping"})
    assert f[8:9] == protocol._TAG_PICKLE
    assert not native_frames.available()
    monkeypatch.setattr(native_frames, "_libs", None)


def test_env_disable_wins_over_present_so(monkeypatch):
    monkeypatch.setenv("RAY_TPU_NATIVE_FRAMES", "0")
    monkeypatch.setattr(rtf, "_active", None)
    rtf.autoenable_from_env()
    assert rtf._active is None


def test_forced_fallback_dispatch_e2e():
    """Satellite: the dispatch plane runs the full submit→execute→done
    path with the .so ABSENT (loader override) — tasks, actors, errors,
    and the flight recorder all behave identically on pure Python.
    Runs in a subprocess so the disarmed state covers the node AND its
    spawned workers."""
    script = r"""
import os
assert os.environ["RAY_TPU_FRAMES_LIB"] == "/nonexistent/librt.so"
from ray_tpu.core import rt_frames
assert rt_frames._active is None, "codec armed despite missing .so"
import ray_tpu
from ray_tpu.core import flight_recorder as fr
rec = fr.enable()
ray_tpu.init(num_cpus=2, num_tpus=0)

@ray_tpu.remote
def add(a, b):
    return a + b

@ray_tpu.remote
def boom():
    raise ValueError("expected")

@ray_tpu.remote
class Counter:
    def __init__(self):
        self.n = 0
    def bump(self):
        self.n += 1
        return self.n

assert ray_tpu.get([add.remote(i, i) for i in range(30)],
                   timeout=120) == [2 * i for i in range(30)]
c = Counter.remote()
assert ray_tpu.get([c.bump.remote() for _ in range(5)],
                   timeout=120) == [1, 2, 3, 4, 5]
try:
    ray_tpu.get(boom.remote(), timeout=120)
    raise AssertionError("error did not propagate")
except Exception as e:
    assert "expected" in str(e)
import time
time.sleep(0.3)
stages = rec.stage_summary()
assert "dispatch" in stages and stages["dispatch"]["n"] >= 30, stages
ray_tpu.shutdown()
print("FALLBACK_E2E_OK")
"""
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu",
               RAY_TPU_FRAMES_LIB="/nonexistent/librt.so")
    out = subprocess.run([sys.executable, "-c", script], env=env,
                         capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "FALLBACK_E2E_OK" in out.stdout


# -- ring -------------------------------------------------------------------

@needs_native
def test_ring_push_drain_fifo(codec):
    ring = codec.make_ring(1 << 16)
    frames = [bytes([i]) * (i + 1) for i in range(50)]
    for f in frames:
        assert ring.push(f)
    assert ring.pending() > 0
    assert ring.drain() == b"".join(frames)
    assert ring.pending() == 0
    assert ring.drain() == b""
    ring.close()


@needs_native
def test_ring_full_falls_back(codec):
    ring = codec.make_ring(4096)
    frame = b"x" * 1500
    pushed = 0
    while ring.push(frame):
        pushed += 1
    assert pushed >= 2
    assert not ring.push(frame)        # full → caller takes locked path
    assert len(ring.drain()) == pushed * len(frame)
    assert ring.push(frame)            # space reclaimed
    ring.close()


@needs_native
def test_ring_concurrent_producers(codec):
    """Python-side MPSC smoke (the heavy TSAN stress lives in
    native/tests/frames_test.cc): N threads push self-describing
    records, one drainer accounts for every byte."""
    ring = codec.make_ring(1 << 16)
    n_threads, per_thread = 4, 2000
    done = threading.Event()
    received = bytearray()

    def producer(tid):
        payload = bytes([tid]) * 40
        for _ in range(per_thread):
            while not ring.push(payload):
                pass   # full: the drainer frees space

    threads = [threading.Thread(target=producer, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()

    def drainer():
        while not done.is_set() or ring.pending():
            received.extend(ring.drain())

    d = threading.Thread(target=drainer)
    d.start()
    for t in threads:
        t.join()
    done.set()
    d.join(timeout=30)
    assert len(received) == n_threads * per_thread * 40
    counts = {t: received.count(bytes([t])) // 1 for t in range(n_threads)}
    for t in range(n_threads):
        assert counts[t] == per_thread * 40
    ring.close()


@needs_native
def test_connection_ring_no_stranded_frame_deterministic(codec):
    """Regression (found as a 1-in-N hang in the 8-node broadcast
    bench): a frame pushed to the ring while ANOTHER thread sat inside
    a plain locked send — whose pre-drain ran before the push landed —
    was stranded until the next send on the connection.  Deterministic
    reproduction: shrink the socket buffer so an ineligible (pickle
    path) send BLOCKS inside its critical section, push a ring frame
    while it is blocked, then drain the receiver.  Without the
    post-release _flush_ring sweep the ring frame never reaches the
    wire."""
    import socket as socketlib
    a, b = socketlib.socketpair()
    a.setsockopt(socketlib.SOL_SOCKET, socketlib.SO_SNDBUF, 8192)
    conn = protocol.Connection(a, encoding="pickle")
    conn.enable_ring()
    big = {"t": "big", "blob": object(), "pad": b"x" * 262144}

    blocker = threading.Thread(target=lambda: conn.send(big))
    blocker.start()
    # wait until the blocker is wedged inside sendall holding the lock
    deadline = time.monotonic() + 10
    while not conn._send_lock.locked():
        assert time.monotonic() < deadline, "blocker never took the lock"
        time.sleep(0.005)
    time.sleep(0.1)
    conn.send({"t": "small", "i": 1})      # ring push; lock is held
    assert conn._ring.pending() > 0        # parked, not yet on the wire

    rx = protocol.Connection(b, encoding="pickle")
    got = [rx.recv(timeout=30) for _ in range(2)]
    blocker.join(timeout=30)
    assert not blocker.is_alive()
    kinds = sorted(m["t"] for m in got)
    assert kinds == ["big", "small"], kinds
    assert conn._ring.pending() == 0
    conn.close()
    rx.close()


@needs_native
def test_connection_ring_no_stranded_frames_mixed_paths(codec):
    """Probabilistic companion of the deterministic stranding test:
    mixed ring-eligible and pickle-fallback messages across threads
    must all arrive, with nothing left in the ring once senders
    stop."""
    import socket as socketlib
    a, b = socketlib.socketpair()
    conn = protocol.Connection(a, encoding="pickle")
    conn.enable_ring()
    n_threads, per_thread = 4, 300
    poison = object()   # ineligible → pickle under the send lock

    def sender(tid):
        for i in range(per_thread):
            if i % 3 == 2:
                conn.send({"t": "mix", "tid": tid, "i": i,
                           "blob": poison})
            else:
                conn.send({"t": "mix", "tid": tid, "i": i})

    # receiver runs CONCURRENTLY (senders would otherwise block on a
    # full socket buffer), but the assertion bites after the join: no
    # trailing send happens once the workers stop, so anything still in
    # the ring at that point would strand forever without the sweep
    rx = protocol.Connection(b, encoding="pickle")
    seen = {t: set() for t in range(n_threads)}

    def receiver():
        for _ in range(n_threads * per_thread):
            m = rx.recv(timeout=30)
            seen[m["tid"]].add(m["i"])

    rthread = threading.Thread(target=receiver)
    rthread.start()
    threads = [threading.Thread(target=sender, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    rthread.join(timeout=60)
    assert not rthread.is_alive(), \
        f"stranded frames: got {sum(len(s) for s in seen.values())}" \
        f"/{n_threads * per_thread}"
    for t in range(n_threads):
        assert seen[t] == set(range(per_thread)), (t, len(seen[t]))
    assert conn._ring.pending() == 0
    conn.close()
    rx.close()


@needs_native
def test_connection_ring_send_combining(codec):
    """End-to-end over a real socketpair: concurrent senders on one
    ring-armed Connection deliver every frame intact (combining must
    never tear or drop a frame)."""
    import socket as socketlib
    a, b = socketlib.socketpair()
    conn = protocol.Connection(a, encoding="pickle")
    conn.enable_ring()
    assert conn._ring is not None, "ring did not arm"
    n_threads, per_thread = 4, 200

    def sender(tid):
        for i in range(per_thread):
            conn.send({"t": "ping", "tid": tid, "i": i})

    threads = [threading.Thread(target=sender, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    rx = protocol.Connection(b, encoding="pickle")
    seen = {t: set() for t in range(n_threads)}
    for _ in range(n_threads * per_thread):
        m = rx.recv(timeout=30)
        seen[m["tid"]].add(m["i"])
    for t in threads:
        t.join()
    for t in range(n_threads):
        assert seen[t] == set(range(per_thread))
    conn.close()
    rx.close()

@needs_native
def test_stamp_fold_depth_boundary_parity(codec):
    """Review-caught divergence: the C stamp fold skipped the depth
    check the Python reference runs on the appended (stage, t) tuple —
    an "fr" list sitting at depth MAX-2 could encode in C (emitting a
    frame decoders reject) while Python fell back to pickle.  Both
    encoders must agree at every depth around the boundary."""
    for fr_depth in (rtf.MAX_DEPTH - 4, rtf.MAX_DEPTH - 3,
                     rtf.MAX_DEPTH - 2, rtf.MAX_DEPTH - 1):
        msg = cur = {}
        for _ in range(fr_depth):
            cur["d"] = {}
            cur = cur["d"]
        cur["fr"] = [("a", 1.0)]
        py = rtf.py_encode_frame(msg, stamp="S", now=2.5)
        nat = codec.encode_frame(msg, stamp="S", now=2.5)
        assert (py is None) == (nat is None), fr_depth
        assert py == nat, fr_depth
        if py is not None:
            # whatever encodes must also decode on both sides
            assert rtf.py_decode_payload(py[8:]) \
                == codec.decode_payload(py[8:])


# -- satellite (round 12): task_done cork FIFO audit ------------------------
#
# Audit result, recorded here: per-link FIFO survives the corked/batched
# done-return leg BY CONSTRUCTION at the service layer — client-bound
# replies and pubsub pushes share one per-rec write buffer appended in
# call order (service._push), and head/peer-bound messages append to one
# per-conn list that _flush_corked concatenates into a SINGLE payload
# (send_batch), which parks as ONE ring record when contended.  The new
# hazard this PR introduced is at the Connection layer: a frame parked
# in the ring by thread T while another thread held the send lock,
# followed by T's next frame taking the direct locked path, would
# reorder T's messages on the wire — protocol.send closes it by parking
# the direct frame behind any pending ring frames ("park ours too").
# The two tests below pin both layers.


@needs_native
def test_per_link_fifo_across_mixed_send_paths(codec):
    """Per-sender FIFO on one ring-armed Connection when consecutive
    sends take DIFFERENT paths: ring park (contended eligible), direct
    locked write (pickle fallback), and send_batch (the corked
    done-return shape).  Delivery alone is covered elsewhere; this
    asserts ORDER."""
    import socket as socketlib
    a, b = socketlib.socketpair()
    conn = protocol.Connection(a, encoding="pickle")
    conn.enable_ring()
    n_threads, per_thread = 4, 240
    poison = object()   # ineligible -> pickle under the send lock

    def sender(tid):
        seq = 0
        while seq < per_thread:
            if seq % 7 == 3:
                k = min(3, per_thread - seq)
                conn.send_batch([{"t": "m", "tid": tid, "seq": seq + j}
                                 for j in range(k)])
                seq += k
            elif seq % 7 == 5:
                conn.send({"t": "m", "tid": tid, "seq": seq, "x": poison})
                seq += 1
            else:
                conn.send({"t": "m", "tid": tid, "seq": seq})
                seq += 1

    rx = protocol.Connection(b, encoding="pickle")
    order = {t: [] for t in range(n_threads)}

    def receiver():
        for _ in range(n_threads * per_thread):
            m = rx.recv(timeout=60)
            order[m["tid"]].append(m["seq"])

    rth = threading.Thread(target=receiver)
    rth.start()
    threads = [threading.Thread(target=sender, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    rth.join(timeout=120)
    assert not rth.is_alive(), "receiver starved: frames lost or stuck"
    for t in range(n_threads):
        assert order[t] == list(range(per_thread)), (
            f"link FIFO broken for sender {t}: "
            f"{[x for x, y in zip(order[t], range(per_thread)) if x != y][:5]}")
    conn.close()
    rx.close()


def test_node_cork_fifo_result_vs_actor_state(rt_init):
    """End-to-end through the REAL node loop: task results and actor
    state updates queued to the same peer link in one loop pass
    (_conn_send -> _flush_corked -> send_batch) must arrive exactly in
    enqueue order.  Runs with or without the native codec; with it, the
    flushed batch additionally crosses the ring-armed send path."""
    import socket as socketlib
    from ray_tpu.core.runtime import get_runtime
    svc = get_runtime().node_service
    assert svc is not None, "driver-mode init should embed a node service"
    a, b = socketlib.socketpair()
    conn = protocol.Connection(a, encoding="pickle")
    conn.enable_ring()   # no-op when the codec is disarmed

    msgs = []
    for i in range(30):
        if i % 3 == 2:
            msgs.append({"t": "actor_state_report", "seq": i,
                         "actor_id": b"\x07" * 22, "state": "alive",
                         "death_cause": None})
        else:
            msgs.append({"t": "remote_result", "seq": i,
                         "task_id": bytes([i]) * 22, "ok": True})

    svc.post(lambda: [svc._conn_send(conn, m) for m in msgs])

    rx = protocol.Connection(b, encoding="pickle")
    got = [rx.recv(timeout=30)["seq"] for _ in msgs]
    assert got == list(range(30)), got
    conn.close()
    rx.close()


@needs_native
def test_oversized_frame_not_starved_by_ring_refill(codec):
    """Review-caught liveness hazard: a frame larger than the ring's
    max record (cap/2) can never push, and the naive park loop only
    exited at pending()==0 — which concurrent parkers kept refilling
    BECAUSE the big-frame sender held the send lock.  The fix
    (_direct_wait) stops NEW parks while the stuck sender drains the
    ring dry, so the wait is bounded and cross-thread wire FIFO is
    kept; this pins that big and small senders both finish promptly
    and each keeps its own order."""
    import socket as socketlib
    a, b = socketlib.socketpair()
    conn = protocol.Connection(a, encoding="pickle")
    conn.enable_ring(capacity=4096)    # max ring record = 2048 bytes
    n_big, n_small = 60, 1200
    big_pad = b"x" * 3000              # frame > cap/2: never parks

    def big_sender():
        for i in range(n_big):
            conn.send({"t": "big", "tid": 0, "seq": i, "pad": big_pad})

    def small_sender():
        for i in range(n_small):
            conn.send({"t": "small", "tid": 1, "seq": i})

    rx = protocol.Connection(b, encoding="pickle")
    order = {0: [], 1: []}

    def receiver():
        for _ in range(n_big + n_small):
            m = rx.recv(timeout=60)
            order[m["tid"]].append(m["seq"])

    rth = threading.Thread(target=receiver)
    rth.start()
    threads = [threading.Thread(target=big_sender),
               threading.Thread(target=small_sender)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive(), "sender starved (park-loop livelock)"
    rth.join(timeout=60)
    assert not rth.is_alive()
    assert order[0] == list(range(n_big))
    assert order[1] == list(range(n_small))
    assert conn._ring.pending() == 0
    conn.close()
    rx.close()
