"""Job submission + runtime env tests (reference analogue:
dashboard/modules/job/tests/test_job_manager.py +
python/ray/tests/test_runtime_env*.py)."""

from __future__ import annotations

import os
import time

import pytest

import ray_tpu


@pytest.fixture
def rt():
    ray_tpu.init(num_cpus=2, num_tpus=0)
    yield ray_tpu
    ray_tpu.shutdown()


def test_task_runtime_env_env_vars(rt):
    @ray_tpu.remote
    def read_env():
        return os.environ.get("MY_FLAG"), os.environ.get("OTHER")

    flagged = read_env.options(
        runtime_env={"env_vars": {"MY_FLAG": "on"}})
    assert ray_tpu.get(flagged.remote(), timeout=60) == ("on", None)
    # the env does not leak into later tasks on the same worker
    assert ray_tpu.get(read_env.remote(), timeout=60) == (None, None)


def test_actor_runtime_env_spans_lifetime(rt):
    @ray_tpu.remote(runtime_env={"env_vars": {"ACTOR_MODE": "tpu"}})
    class A:
        def mode(self):
            return os.environ.get("ACTOR_MODE")

    a = A.remote()
    assert ray_tpu.get(a.mode.remote(), timeout=90) == "tpu"
    assert ray_tpu.get(a.mode.remote(), timeout=60) == "tpu"


def test_working_dir_package_roundtrip(rt, tmp_path):
    (tmp_path / "mod").mkdir()
    (tmp_path / "mod" / "__init__.py").write_text("VALUE = 41\n")
    (tmp_path / "helper.py").write_text("def answer():\n    return 42\n")

    from ray_tpu.runtime_env import (ensure_package, package_directory,
                                     upload_package)
    pkg = package_directory(str(tmp_path))
    h = upload_package(rt.get_runtime().client, pkg)
    # idempotent
    assert upload_package(rt.get_runtime().client, pkg) == h

    @ray_tpu.remote(runtime_env={"working_dir": h})
    def use_pkg():
        import helper
        import mod
        return helper.answer() + mod.VALUE

    assert ray_tpu.get(use_pkg.remote(), timeout=90) == 83

    dest = ensure_package(rt.get_runtime().client, h)
    assert os.path.exists(os.path.join(dest, "helper.py"))


def test_job_submission_lifecycle(rt, tmp_path):
    from ray_tpu.job import JobStatus, JobSubmissionClient

    (tmp_path / "script.py").write_text(
        "import os\n"
        "print('job says', os.environ.get('GREETING'))\n"
        "print('cwd has script:', os.path.exists('script.py'))\n")

    client = JobSubmissionClient()
    job_id = client.submit_job(
        entrypoint="python script.py",
        runtime_env={"working_dir": str(tmp_path),
                     "env_vars": {"GREETING": "hello"}})
    status = client.wait_until_finished(job_id, timeout=180)
    logs = client.get_job_logs(job_id)
    assert status == JobStatus.SUCCEEDED, logs
    assert "job says hello" in logs
    assert "cwd has script: True" in logs

    infos = {j.job_id for j in client.list_jobs()}
    assert job_id in infos


def test_job_failure_and_stop(rt):
    from ray_tpu.job import JobStatus, JobSubmissionClient

    client = JobSubmissionClient()
    bad = client.submit_job(entrypoint="python -c 'raise SystemExit(3)'")
    assert client.wait_until_finished(bad, timeout=120) == JobStatus.FAILED
    assert "exit code 3" in client.get_job_info(bad).message

    slow = client.submit_job(
        entrypoint="python -c 'import time; print(\"go\", flush=True); "
                   "time.sleep(120)'")
    deadline = time.time() + 60
    while time.time() < deadline:
        if client.get_job_status(slow) == JobStatus.RUNNING \
                and "go" in client.get_job_logs(slow):
            break
        time.sleep(0.25)
    assert client.stop_job(slow)
    assert client.wait_until_finished(slow, timeout=120) == JobStatus.STOPPED


def test_job_driver_joins_cluster(rt, tmp_path):
    """A job's entrypoint is a full driver: it joins the SAME cluster
    through RAY_TPU_ADDRESS and runs its own tasks."""
    from ray_tpu.job import JobStatus, JobSubmissionClient

    (tmp_path / "drv.py").write_text(
        "import ray_tpu\n"
        "ray_tpu.init()\n"   # RAY_TPU_ADDRESS from the supervisor
        "@ray_tpu.remote\n"
        "def double(x):\n"
        "    return x * 2\n"
        "print('result', ray_tpu.get(double.remote(21), timeout=120))\n")

    client = JobSubmissionClient()
    job_id = client.submit_job(entrypoint="python drv.py",
                               runtime_env={"working_dir": str(tmp_path)})
    status = client.wait_until_finished(job_id, timeout=240)
    logs = client.get_job_logs(job_id)
    assert status == JobStatus.SUCCEEDED, logs
    assert "result 42" in logs
