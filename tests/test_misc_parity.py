"""Tests for small parity surfaces: TransformersTrainer, accelerators,
check_serialize, usage stats (reference test models:
python/ray/train/tests/test_transformers_trainer.py,
python/ray/tests/test_serialization_checker.py)."""

import json
import os
import threading

import numpy as np
import pytest


class TestAccelerators:
    def test_constants_and_resource_names(self):
        from ray_tpu.util import accelerators as acc
        assert acc.TPU_V5E == "TPU-V5E"
        assert acc.accelerator_resource(acc.TPU_V4) == \
            "accelerator_type:TPU-V4"
        assert acc.is_known_accelerator(acc.NVIDIA_TESLA_A100)
        assert not acc.is_known_accelerator("GTX-9090")

    def test_detect_does_not_crash(self):
        from ray_tpu.util.accelerators import detect_tpu_type
        assert isinstance(detect_tpu_type(), str)


class TestCheckSerialize:
    def test_serializable_passes(self):
        from ray_tpu.util.check_serialize import inspect_serializability
        ok, failures = inspect_serializability(lambda x: x + 1,
                                               _print=lambda *a: None)
        assert ok and not failures

    def test_finds_offending_closure(self):
        from ray_tpu.util.check_serialize import inspect_serializability
        lock = threading.Lock()   # unpicklable

        def fn():
            return lock

        ok, failures = inspect_serializability(
            fn, _print=lambda *a: None)
        assert not ok
        assert any(f.name == "lock" for f in failures)


class TestUsageStats:
    def test_record_and_write(self, tmp_path, monkeypatch):
        from ray_tpu import usage_stats as us
        monkeypatch.setenv("RAY_TPU_USAGE_STATS_ENABLED", "1")
        us.record_library_usage("train")
        us.record_extra_usage_tag("test", "yes")
        path = us.write_usage_record(str(tmp_path))
        with open(path) as f:
            rec = json.loads(f.readlines()[-1])
        assert "train" in rec["libraries"]
        assert rec["tags"]["test"] == "yes"

    def test_opt_out(self, tmp_path, monkeypatch):
        from ray_tpu import usage_stats as us
        monkeypatch.setenv("RAY_TPU_USAGE_STATS_ENABLED", "0")
        assert us.write_usage_record(str(tmp_path / "x")) is None
        assert not (tmp_path / "x").exists()


def test_simpleq_smoke():
    from ray_tpu.rllib import SimpleQ, SimpleQConfig
    algo = SimpleQConfig(env="CartPole-v1", learning_starts=16,
                         batch_size=8, rollout_length=8, seed=0).build()
    assert isinstance(algo, SimpleQ)
    assert not algo.config.double_q and not algo.config.dueling
    r = algo.train()
    assert r["steps_this_iter"] > 0


def test_integration_callbacks_gated():
    """Without wandb/mlflow installed the callbacks raise an actionable
    ImportError at construction (reference behavior)."""
    from ray_tpu.tune.integration import (MLflowLoggerCallback,
                                          WandbLoggerCallback)
    try:
        import wandb  # noqa: F401
    except ImportError:
        with pytest.raises(ImportError, match="wandb"):
            WandbLoggerCallback(project="x")
    try:
        import mlflow  # noqa: F401
    except ImportError:
        with pytest.raises(ImportError, match="mlflow"):
            MLflowLoggerCallback()


@pytest.mark.slow
def test_transformers_trainer(rt_init, tmp_path):
    transformers = pytest.importorskip("transformers")
    import torch
    from torch.utils.data import Dataset as TorchDataset

    from ray_tpu.train import RunConfig, ScalingConfig
    from ray_tpu.train.huggingface import TransformersTrainer

    def trainer_init(config):
        cfg = transformers.BertConfig(
            vocab_size=64, hidden_size=16, num_hidden_layers=1,
            num_attention_heads=2, intermediate_size=32,
            max_position_embeddings=32, num_labels=2)
        model = transformers.BertForSequenceClassification(cfg)

        class RandomSet(TorchDataset):
            def __len__(self):
                return 16

            def __getitem__(self, i):
                g = torch.Generator().manual_seed(i)
                return {"input_ids": torch.randint(
                            0, 64, (16,), generator=g),
                        "attention_mask": torch.ones(16,
                                                     dtype=torch.long),
                        "labels": torch.tensor(i % 2)}

        args = transformers.TrainingArguments(
            output_dir=config["output_dir"], max_steps=3,
            per_device_train_batch_size=4, logging_steps=1,
            report_to=[], use_cpu=True, disable_tqdm=True)
        return transformers.Trainer(model=model, args=args,
                                    train_dataset=RandomSet())

    trainer = TransformersTrainer(
        trainer_init,
        trainer_init_config={"output_dir": str(tmp_path / "hf")},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(storage_path=str(tmp_path)))
    result = trainer.fit()
    assert result.metrics["global_step"] == 3
    assert np.isfinite(result.metrics["training_loss"])
    assert result.checkpoint is not None
    sd = result.checkpoint.to_dict()["state_dict"]
    assert any("bert" in k for k in sd)
