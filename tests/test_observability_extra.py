"""Round-5 gap closers: sampling profiler + flamegraphs, spill
backends, container runtime-env gating, TF/Horovod backend contracts,
dashboard metrics history."""

import json
import time
import urllib.request

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def rt():
    ray_tpu.init(num_cpus=2, num_tpus=0,
                 object_store_memory=64 * 1024 * 1024)
    yield ray_tpu
    ray_tpu.shutdown()


# -- sampling profiler ------------------------------------------------------

def test_sample_folded_captures_own_stacks():
    from ray_tpu.util.profiling import sample_folded

    def busy(deadline):
        x = 0.0
        while time.monotonic() < deadline:
            x += 1.0
        return x

    import threading
    t = threading.Thread(target=busy, args=(time.monotonic() + 1.0,),
                         name="busy-thread")
    t.start()
    folded = sample_folded(duration=0.5, hz=200)
    t.join()
    assert any("busy" in line for line in folded.splitlines()), folded
    # folded format: path;path;... COUNT
    for line in folded.splitlines():
        assert line.rsplit(" ", 1)[1].isdigit()


def test_flamegraph_svg_renders():
    from ray_tpu.util.profiling import flamegraph_svg
    folded = "main;work;inner 10\nmain;work;other 5\nmain;idle 3"
    svg = flamegraph_svg(folded)
    assert svg.startswith("<svg") and svg.endswith("</svg>")
    assert svg.count("<rect") >= 5          # root bg + frames
    assert "inner" in svg and "&lt;" not in "inner"


def test_profile_live_worker_end_to_end(rt):
    from ray_tpu.core.observer import observer_query
    from ray_tpu.core.runtime import get_runtime

    @ray_tpu.remote
    def spin(sec):
        import math
        t0 = time.time()
        x = 0.0
        while time.time() - t0 < sec:
            x += math.sin(x) ** 2
        return x

    ref = spin.remote(5.0)
    time.sleep(1.0)
    svc = get_runtime().node_service
    pid = next(c.pid for c in svc.clients.values()
               if c.kind == "worker" and c.state == "busy")
    (reply,) = observer_query(
        svc.address,
        [{"t": "profile_worker", "pid": pid, "duration": 1.0}],
        request_timeout=60)
    folded = reply.get("folded", "")
    assert any("spin" in ln for ln in folded.splitlines()), folded
    ray_tpu.get(ref, timeout=60)


# -- spill backends ---------------------------------------------------------

def test_file_spill_backend_roundtrip(tmp_path):
    from ray_tpu.core.spill import make_spill_backend
    b = make_spill_backend("", str(tmp_path / "spill"))
    loc = b.put("abc", b"hello world")
    assert b.get(loc) == b"hello world"
    b.delete(loc)
    with pytest.raises(FileNotFoundError):
        b.get(loc)


def test_s3_spill_backend_with_stub_client():
    from ray_tpu.core.spill import S3SpillBackend

    class StubS3:
        def __init__(self):
            self.objects = {}

        def put_object(self, Bucket, Key, Body):
            self.objects[(Bucket, Key)] = Body

        def get_object(self, Bucket, Key):
            import io
            return {"Body": io.BytesIO(self.objects[(Bucket, Key)])}

        def delete_object(self, Bucket, Key):
            self.objects.pop((Bucket, Key), None)

    stub = StubS3()
    b = S3SpillBackend("s3://bkt/spill/prefix", client=stub)
    loc = b.put("objhex", b"\x00\x01payload")
    assert loc == "s3://bkt/spill/prefix/objhex"
    assert b.get(loc) == b"\x00\x01payload"
    b.delete(loc)
    assert not stub.objects


def test_unknown_spill_scheme_rejected_at_config():
    from ray_tpu.core.spill import make_spill_backend
    with pytest.raises(ValueError, match="scheme"):
        make_spill_backend("gs://nope/x", "/tmp")


def test_spill_restore_through_backend(rt):
    """A real put > store budget spills through the backend and restores
    on get (the end-to-end spill path with the new indirection)."""
    from ray_tpu.core.runtime import get_runtime
    svc = get_runtime().node_service
    before = svc.store.stats()["num_spilled"]
    refs = [ray_tpu.put(np.ones(6 << 20, np.uint8)) for _ in range(14)]
    out = ray_tpu.get(refs[0], timeout=120)     # likely spilled: restore
    assert out.nbytes == 6 << 20
    assert svc.store.stats()["num_spilled"] > before
    ray_tpu.free(refs)


# -- container runtime env --------------------------------------------------

def test_container_env_validation():
    from ray_tpu.runtime_env import validate
    ok = validate({"container": {"image": "img:tag",
                                 "run_options": ["--cpus=2"]}})
    assert ok["container"]["image"] == "img:tag"
    with pytest.raises(ValueError, match="container"):
        validate({"container": "img:tag"})
    with pytest.raises(ValueError, match="container"):
        validate({"container": {"run_options": []}})


def test_container_command_construction():
    from ray_tpu.runtime_env import container_command
    argv = container_command(
        {"image": "repo/img:1", "run_options": ["--cpus=2"]},
        ["python", "-m", "ray_tpu.core.worker", "--address", "a:1"],
        "/tmp/ray_tpu/session_x", runtime="podman")
    assert argv[0] == "podman" and argv[1] == "run"
    assert "--network=host" in argv and "--ipc=host" in argv
    assert "-v" in argv and "/tmp/ray_tpu/session_x:/tmp/ray_tpu/session_x" in argv
    assert "--cpus=2" in argv
    assert argv[argv.index("repo/img:1") + 1] == "python"
    assert "RAY_TPU_CONTAINER_IMAGE=repo/img:1" in argv


def test_container_command_gated_without_runtime(monkeypatch):
    import shutil
    from ray_tpu.runtime_env import container_command
    monkeypatch.setattr(shutil, "which", lambda _: None)
    with pytest.raises(RuntimeError, match="podman nor docker"):
        container_command({"image": "x"}, ["cmd"], "/tmp/s")


def test_container_task_fails_with_clear_error(rt):
    @ray_tpu.remote(runtime_env={"container": {"image": "repo/img:9"}})
    def f():
        return 1

    with pytest.raises(Exception, match="container"):
        ray_tpu.get(f.remote(), timeout=120)


# -- TF / Horovod backend contracts ----------------------------------------

def test_tf_config_assembly():
    from ray_tpu.train import build_tf_config
    cfg = json.loads(build_tf_config(["h1:1", "h2:2", "h3:3"], 1))
    assert cfg["cluster"]["worker"] == ["h1:1", "h2:2", "h3:3"]
    assert cfg["task"] == {"type": "worker", "index": 1}


def test_tensorflow_trainer_sets_tf_config_on_every_worker(rt):
    """The backend's full contract without tensorflow itself: every
    rank's loop sees a consistent TF_CONFIG cluster spec (reference:
    tensorflow/config.py:21 — that IS the backend)."""
    import os as _os
    from ray_tpu.train import ScalingConfig, TensorflowTrainer
    from ray_tpu.train import session as ts

    def loop():
        cfg = json.loads(_os.environ["TF_CONFIG"])
        ts.report({"rank": cfg["task"]["index"],
                   "workers": len(cfg["cluster"]["worker"])})

    result = TensorflowTrainer(
        loop, scaling_config=ScalingConfig(num_workers=2)).fit()
    assert result.metrics["workers"] == 2


def test_horovod_env_layout():
    from ray_tpu.train import build_horovod_env
    hosts = ["10.0.0.1", "10.0.0.1", "10.0.0.2"]
    env1 = build_horovod_env(hosts, 1, "10.0.0.1", 9999)
    assert env1["HOROVOD_RANK"] == "1"
    assert env1["HOROVOD_SIZE"] == "3"
    assert env1["HOROVOD_LOCAL_RANK"] == "1"   # 2nd worker on host .1
    assert env1["HOROVOD_LOCAL_SIZE"] == "2"
    assert env1["HOROVOD_CROSS_SIZE"] == "2"
    env2 = build_horovod_env(hosts, 2, "10.0.0.1", 9999)
    assert env2["HOROVOD_LOCAL_RANK"] == "0"
    assert env2["HOROVOD_GLOO_RENDEZVOUS_PORT"] == "9999"


def test_horovod_trainer_env_contract(rt):
    import os as _os
    from ray_tpu.train import HorovodTrainer, ScalingConfig
    from ray_tpu.train import session as ts

    def loop():
        ts.report({"rank": int(_os.environ["HOROVOD_RANK"]),
                   "size": int(_os.environ["HOROVOD_SIZE"])})

    result = HorovodTrainer(
        loop, scaling_config=ScalingConfig(num_workers=2)).fit()
    assert result.metrics["size"] == 2


# -- dashboard metrics history ---------------------------------------------

def test_dashboard_metrics_history(rt):
    from ray_tpu.core.runtime import get_runtime
    from ray_tpu.dashboard import Dashboard

    svc = get_runtime().node_service
    db = Dashboard(svc.address, port=0, history_interval_s=0.3)
    db.start()
    try:
        @ray_tpu.remote
        def hold(s):
            time.sleep(s)
            return 1
        ref = hold.remote(1.5)
        time.sleep(1.2)
        hist = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{db.port}/api/metrics/history",
            timeout=10).read())
        assert len(hist) >= 2
        assert {"ts", "cpu_used", "tasks_running",
                "store_used_mb"} <= set(hist[-1])
        assert any(h["cpu_used"] > 0 for h in hist)
        ray_tpu.get(ref, timeout=60)
    finally:
        db.stop()
