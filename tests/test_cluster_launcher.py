"""Cluster launcher: up/down/attach/exec from YAML configs (reference:
python/ray/scripts/scripts.py up:1216 down:1292 attach:1376 exec:1674
over autoscaler/_private/commands.py)."""

import json
import os

import pytest

from ray_tpu.autoscaler import commands as C


@pytest.fixture(autouse=True)
def isolated_state(tmp_path, monkeypatch):
    monkeypatch.setattr(C, "_STATE_DIR", str(tmp_path / "clusters"))


def _write_cfg(tmp_path, **over):
    import yaml
    cfg = {"cluster_name": "t1",
           "provider": {"type": "tpu_pod", "project": "p",
                        "zone": "us-central2-b"},
           "min_workers": 0, "max_workers": 3, "initial_workers": 2}
    cfg.update(over)
    p = tmp_path / "cluster.yaml"
    p.write_text(yaml.safe_dump(cfg))
    return str(p)


class StubProvider:
    """Records lifecycle calls; mimics the TpuPodNodeProvider surface."""

    def __init__(self):
        self.calls = []
        self._n = 0
        self.live = set()

    def create_head(self, node_config, port=6380):
        self.calls.append(("create_head", port))
        self.live.add("head-1")
        return "head-1", f"10.0.0.1:{port}"

    def create_node(self, head_address, node_config):
        self._n += 1
        nid = f"w-{self._n}"
        self.calls.append(("create_node", head_address, nid))
        self.live.add(nid)
        return nid

    def terminate_node(self, node_id):
        self.calls.append(("terminate", node_id))
        self.live.discard(node_id)

    def non_terminated_nodes(self):
        return []

    def exec_on(self, node_id, command, all_workers=False):
        self.calls.append(("exec", node_id, command, all_workers))
        return f"ran on {node_id}"

    def ssh_command(self, node_id):
        return ["ssh", node_id]


def test_config_validation(tmp_path):
    import yaml
    bad = tmp_path / "bad.yaml"
    bad.write_text(yaml.safe_dump({"provider": {"type": "tpu_pod"}}))
    with pytest.raises(C.ClusterConfigError, match="cluster_name"):
        C.load_cluster_config(str(bad))
    bad.write_text(yaml.safe_dump({"cluster_name": "x",
                                   "provider": {"type": "nope"}}))
    with pytest.raises(C.ClusterConfigError, match="provider.type"):
        C.load_cluster_config(str(bad))
    bad.write_text(yaml.safe_dump({"cluster_name": "x",
                                   "provider": {"type": "tpu_pod"}}))
    with pytest.raises(C.ClusterConfigError, match="project"):
        C.load_cluster_config(str(bad))
    bad.write_text(yaml.safe_dump({
        "cluster_name": "x", "min_workers": 3, "max_workers": 1,
        "provider": {"type": "tpu_pod", "project": "p", "zone": "z"}}))
    with pytest.raises(C.ClusterConfigError, match="min_workers"):
        C.load_cluster_config(str(bad))


def test_up_exec_attach_down_lifecycle(tmp_path):
    cfg = C.load_cluster_config(_write_cfg(tmp_path))
    prov = StubProvider()
    logs = []

    state = C.up(cfg, provider=prov, log=logs.append)
    assert state["head_address"] == "10.0.0.1:6380"
    assert state["workers"] == ["w-1", "w-2"]
    assert ("create_head", 6380) in prov.calls
    assert ("create_node", "10.0.0.1:6380", "w-1") in prov.calls

    # state persisted: a second up is idempotent on the head
    state2 = C.up(cfg, provider=prov, log=logs.append)
    assert state2["head_id"] == "head-1"
    assert prov.calls.count(("create_head", 6380)) == 1

    out = C.exec_cmd(cfg, "hostname", provider=prov)
    assert out == "ran on head-1"
    assert ("exec", "head-1", "hostname", False) in prov.calls

    out = C.exec_cmd(cfg, "uptime", provider=prov, on_head=False)
    assert out == "ran on w-1\nran on w-2"

    assert C.attach_argv(cfg, provider=prov) == ["ssh", "head-1"]

    C.down(cfg, provider=prov, log=logs.append)
    assert prov.live == set()
    assert C.load_state("t1") is None


def test_down_partial_failure_keeps_tearing_down(tmp_path):
    cfg = C.load_cluster_config(_write_cfg(tmp_path))
    prov = StubProvider()
    C.up(cfg, provider=prov, log=lambda *_: None)

    orig = prov.terminate_node
    def flaky(nid):
        if nid == "w-1":
            raise RuntimeError("gcloud transient")
        orig(nid)
    prov.terminate_node = flaky

    C.down(cfg, provider=prov, log=lambda *_: None)
    # w-2 and the head still torn down; state cleared
    assert "w-2" not in prov.live and "head-1" not in prov.live
    assert C.load_state("t1") is None


def test_submit_uploads_then_runs(tmp_path):
    cfg = C.load_cluster_config(_write_cfg(tmp_path))
    prov = StubProvider()
    C.up(cfg, provider=prov, log=lambda *_: None)
    script = tmp_path / "job.py"
    script.write_text("print('hi')\n")
    C.submit(cfg, str(script), provider=prov, log=lambda *_: None)
    execs = [c for c in prov.calls if c[0] == "exec"]
    import base64
    assert base64.b64encode(b"print('hi')\n").decode() in execs[-2][2]
    assert execs[-1][2].startswith("python /tmp/ray_tpu_submit_")


def test_tpu_pod_provider_head_lifecycle(monkeypatch):
    """create_head over the stubbed gcloud CLI: create → READY →
    bootstrap head on worker 0 → describe for the internal IP."""
    import shutil as _shutil
    from ray_tpu.autoscaler import tpu_pod_provider as tp

    monkeypatch.setattr(_shutil, "which", lambda _: "/usr/bin/gcloud")
    calls = []

    def fake_run(self, *args, timeout=600.0):
        calls.append(args)
        if args[0] == "describe":
            return json.dumps({"state": "READY", "networkEndpoints":
                               [{"ipAddress": "10.1.2.3"}]})
        if args[0] == "ssh" and any("pgrep" in a for a in args):
            return "HEAD_ALIVE\n"
        return "{}"

    monkeypatch.setattr(tp.TpuPodNodeProvider, "_run", fake_run)
    p = tp.TpuPodNodeProvider(project="p", zone="z")
    p._poll_s = 0.01
    nid, addr = p.create_head({}, port=6380)
    assert nid.startswith("ray-tpu-head-")
    assert addr == "10.1.2.3:6380"
    boot = next(c for c in calls if c[0] == "ssh"
                and not any("pgrep" in a for a in c))
    assert any("--worker=0" in a for a in boot)
    assert any("start --head" in a for a in boot)
    assert p.exec_on(nid, "echo hi") == "{}"
    assert p.ssh_command(nid)[:6] == ["gcloud", "compute", "tpus",
                                      "tpu-vm", "ssh", nid]


def test_local_provider_end_to_end(tmp_path):
    """`provider.type: local`: a real head process + a real worker node
    process come up, a driver connects and runs a task, down() reaps."""
    import time
    import yaml

    import ray_tpu
    from ray_tpu.autoscaler.node_provider import LocalNodeProvider

    cfgp = tmp_path / "local.yaml"
    cfgp.write_text(yaml.safe_dump({
        "cluster_name": "loc1",
        "provider": {"type": "local", "base_dir": str(tmp_path / "nodes")},
        "initial_workers": 1,
        "worker_nodes": {"num_cpus": 2}}))
    cfg = C.load_cluster_config(str(cfgp))
    prov = LocalNodeProvider(base_dir=str(tmp_path / "nodes"))
    try:
        state = C.up(cfg, provider=prov, log=lambda *_: None)
        # join the launched cluster through the worker node's address —
        # resolve it by polling the head for membership
        ray_tpu.init(address=_wait_node_addr(state, prov))

        @ray_tpu.remote
        def f():
            return "up"
        assert ray_tpu.get(f.remote(), timeout=120) == "up"
        ray_tpu.shutdown()
    finally:
        C.down(cfg, provider=prov, log=lambda *_: None)
    assert prov.non_terminated_nodes() == []


def _wait_node_addr(state, prov, timeout=60):
    """The driver connects to a NODE service; ask the head for one."""
    import time

    from ray_tpu.core import protocol

    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            conn = protocol.connect(state["head_address"], timeout=5.0)
            conn.send({"t": "state", "what": "nodes", "reqid": 1})
            reply = conn.recv(timeout=5.0)
            conn.close()
            for n in reply.get("data") or []:
                if n.get("alive") and n.get("address"):
                    return n["address"]
        except Exception:
            pass
        time.sleep(0.5)
    raise RuntimeError("no alive node joined the launched head")
