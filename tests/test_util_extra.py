"""Tests for util parity modules: multiprocessing.Pool shim, joblib
backend, ParallelIterator (reference test models:
python/ray/tests/test_multiprocessing.py, test_joblib.py, test_iter.py).
"""

import pytest

import ray_tpu
from ray_tpu.util import iter as rt_iter
from ray_tpu.util.multiprocessing import Pool


@pytest.fixture(autouse=True)
def _rt(rt_init):
    yield


def _sq(x):
    return x * x


def _add(a, b):
    return a + b


class TestPool:
    def test_map(self):
        with Pool(2) as p:
            assert p.map(_sq, range(10)) == [x * x for x in range(10)]

    def test_apply_and_async(self):
        with Pool(2) as p:
            assert p.apply(_add, (2, 3)) == 5
            r = p.apply_async(_add, (10, 20))
            assert r.get(timeout=60) == 30
            assert r.successful()

    def test_starmap(self):
        with Pool(2) as p:
            assert p.starmap(_add, [(1, 2), (3, 4)]) == [3, 7]

    def test_imap_ordered(self):
        with Pool(2) as p:
            assert list(p.imap(_sq, range(8), chunksize=3)) == \
                [x * x for x in range(8)]

    def test_imap_unordered(self):
        with Pool(2) as p:
            got = sorted(p.imap_unordered(_sq, range(8), chunksize=2))
            assert got == sorted(x * x for x in range(8))

    def test_initializer(self):
        def init(v):
            import os
            os.environ["_POOL_INIT_V"] = str(v)

        def read(_):
            import os
            return os.environ.get("_POOL_INIT_V")

        with Pool(2, initializer=init, initargs=(7,)) as p:
            assert p.map(read, range(4)) == ["7"] * 4

    def test_map_error_propagates(self):
        def boom(x):
            raise ValueError("boom")
        with Pool(2) as p:
            with pytest.raises(Exception, match="boom"):
                p.map(boom, range(4))


class TestParallelIterator:
    def test_from_items_gather_sync(self):
        it = rt_iter.from_items(list(range(20)), num_shards=3)
        assert sorted(it.gather_sync()) == list(range(20))

    def test_for_each_filter_batch(self):
        it = (rt_iter.from_range(12, num_shards=2)
              .for_each(lambda x: x * 2)
              .filter(lambda x: x % 3 == 0)
              .batch(2))
        flat = [x for b in it.gather_sync() for x in b]
        assert sorted(flat) == sorted(
            x * 2 for x in range(12) if (x * 2) % 3 == 0)

    def test_flatten_combine(self):
        it = rt_iter.from_items([[1, 2], [3, 4]], num_shards=2).flatten()
        assert sorted(it.gather_sync()) == [1, 2, 3, 4]
        it2 = rt_iter.from_range(3, num_shards=1).combine(
            lambda x: [x, x * 10])
        assert list(it2.gather_sync()) == [0, 0, 1, 10, 2, 20]

    def test_gather_async(self):
        it = rt_iter.from_range(30, num_shards=3).for_each(lambda x: x + 1)
        assert sorted(it.gather_async(num_async=2)) == list(range(1, 31))

    def test_local_shuffle_preserves_multiset(self):
        it = rt_iter.from_range(50, num_shards=2).local_shuffle(
            shuffle_buffer_size=10, seed=0)
        assert sorted(it.gather_sync()) == list(range(50))

    def test_take_and_shards(self):
        it = rt_iter.from_range(100, num_shards=4)
        assert len(it.take(5)) == 5
        shards = it.for_each(lambda x: -x).shards()
        assert len(shards) == 4
        assert sorted(sum((list(s) for s in shards), [])) == \
            sorted(-x for x in range(100))

    def test_union_and_repartition(self):
        a = rt_iter.from_items([1, 2], num_shards=1)
        b = rt_iter.from_items([3, 4], num_shards=1)
        u = a.union(b)
        assert u.num_shards() == 2
        assert sorted(u.gather_sync()) == [1, 2, 3, 4]
        r = rt_iter.from_range(10, num_shards=2).repartition(5)
        assert r.num_shards() == 5
        assert sorted(r.gather_sync()) == list(range(10))

    def test_repeat(self):
        it = rt_iter.from_items([1, 2, 3], num_shards=1, repeat=True)
        assert it.gather_sync().take(7) == [1, 2, 3, 1, 2, 3, 1]


class TestJoblib:
    def test_backend_registers_and_runs(self):
        joblib = pytest.importorskip("joblib")
        from ray_tpu.util.joblib import register_ray
        register_ray()
        with joblib.parallel_backend("ray", n_jobs=2):
            out = joblib.Parallel()(
                joblib.delayed(_sq)(i) for i in range(6))
        assert out == [x * x for x in range(6)]
