"""Data layer tests (reference analogue: python/ray/data/tests —
test_dataset.py map/filter/shuffle/split, preprocessor tests)."""
import numpy as np
import pytest

from ray_tpu import data as rd
from ray_tpu.data import (BatchMapper, Chain, Concatenator, LabelEncoder,
                          StandardScaler)


def test_range_count_take():
    ds = rd.range(100, parallelism=7)
    assert ds.count() == 100
    rows = ds.take(5)
    assert [r["id"] for r in rows] == [0, 1, 2, 3, 4]


def test_from_items_rows():
    ds = rd.from_items([{"a": i, "b": i * 2} for i in range(10)])
    assert ds.count() == 10
    assert ds.take(1)[0]["b"] == 0
    assert set(ds.schema().keys()) == {"a", "b"}


def test_map_batches_and_filter():
    ds = (rd.range(50)
          .map_batches(lambda b: {"id": b["id"], "sq": b["id"] ** 2})
          .filter(lambda r: r["sq"] % 2 == 0))
    rows = ds.take_all()
    assert all(r["sq"] == r["id"] ** 2 for r in rows)
    assert all(r["sq"] % 2 == 0 for r in rows)


def test_repartition_shuffle_sort():
    ds = rd.range(40, parallelism=4).repartition(8)
    assert ds.stats()["num_blocks"] == 8
    sh = rd.range(40).random_shuffle(seed=0)
    ids = [r["id"] for r in sh.take_all()]
    assert sorted(ids) == list(range(40))
    assert ids != list(range(40))
    st = sh.sort("id")
    assert [r["id"] for r in st.take(3)] == [0, 1, 2]


def test_split_even():
    parts = rd.range(10).split(3)
    counts = [p.count() for p in parts]
    assert sum(counts) == 10
    assert counts[:2] == [3, 3]


def test_iter_batches_sizes():
    ds = rd.range(25, parallelism=3)
    sizes = [len(b["id"]) for b in ds.iter_batches(batch_size=10)]
    assert sizes == [10, 10, 5]
    sizes = [len(b["id"])
             for b in ds.iter_batches(batch_size=10, drop_last=True)]
    assert sizes == [10, 10]


def test_iter_batches_sharded_mesh():
    import jax
    from ray_tpu.parallel.mesh import create_mesh
    mesh = create_mesh({"dp": 4}, devices=jax.devices("cpu")[:4])
    ds = rd.from_numpy({"x": np.arange(64, dtype=np.float32)})
    batches = list(ds.iter_batches_sharded(mesh, batch_size=16))
    assert len(batches) == 4
    x = batches[0]["x"]
    assert isinstance(x, jax.Array)
    assert x.sharding.num_devices == 4


def test_csv_parquet_roundtrip(tmp_path):
    import pandas as pd
    p = tmp_path / "t.csv"
    pd.DataFrame({"a": [1, 2, 3], "b": [4.0, 5.0, 6.0]}).to_csv(
        p, index=False)
    ds = rd.read_csv(str(p))
    assert ds.count() == 3
    paths = ds.write_parquet(str(tmp_path / "pq"))
    ds2 = rd.read_parquet(paths)
    assert ds2.count() == 3
    assert ds2.take(1)[0]["b"] == 4.0


def test_preprocessors():
    ds = rd.from_numpy({"x": np.arange(10, dtype=np.float64),
                        "label": np.array(list("abbaabbaba"))})
    sc = StandardScaler(["x"])
    out = sc.fit_transform(ds)
    xs = np.array([r["x"] for r in out.take_all()])
    assert abs(xs.mean()) < 1e-9
    le = LabelEncoder("label")
    enc = le.fit_transform(ds)
    labs = {r["label"] for r in enc.take_all()}
    assert labs == {0, 1}


def test_chain_and_concatenator():
    ds = rd.from_numpy({"x": np.arange(8, dtype=np.float64),
                        "y": np.arange(8, dtype=np.float64) * 3})
    chain = Chain(StandardScaler(["x", "y"]),
                  Concatenator(["x", "y"], "features"))
    out = chain.fit_transform(ds)
    row = out.take(1)[0]
    assert row["features"].shape == (2,)


def test_map_batches_as_tasks(rt_init):
    ds = rd.range(20, parallelism=4).map_batches(
        lambda b: {"id": b["id"] + 1})
    assert ds.materialize(parallelism="tasks").count() == 20


# -- new datasources -------------------------------------------------------

def test_json_roundtrip(tmp_path):
    ds = rd.from_numpy({"a": np.arange(6), "b": np.arange(6) * 0.5})
    paths = ds.write_json(str(tmp_path / "j"))
    back = rd.read_json(str(tmp_path / "j"))
    assert back.count() == 6
    assert back.take(1)[0]["a"] == 0


def test_csv_roundtrip(tmp_path):
    ds = rd.from_numpy({"x": np.arange(5)})
    ds.write_csv(str(tmp_path / "c"))
    back = rd.read_csv(str(tmp_path / "c"))
    assert back.count() == 5


def test_numpy_roundtrip(tmp_path):
    ds = rd.from_numpy(np.arange(12).reshape(6, 2))
    ds.write_numpy(str(tmp_path / "n"))
    back = rd.read_numpy(str(tmp_path / "n"))
    assert back.count() == 6


def test_read_text_and_binary(tmp_path):
    p = tmp_path / "f.txt"
    p.write_text("hello\nworld\n")
    ds = rd.read_text(str(p))
    assert [r["text"] for r in ds.take_all()] == ["hello", "world"]
    b = rd.read_binary_files(str(p), include_paths=True)
    row = b.take(1)[0]
    assert row["bytes"] == p.read_bytes() and row["path"].endswith("f.txt")


def test_from_to_pandas():
    import pandas as pd
    df = pd.DataFrame({"a": [1, 2, 3], "b": ["x", "y", "z"]})
    ds = rd.from_pandas(df)
    assert ds.count() == 3
    back = ds.to_pandas()
    assert list(back["a"]) == [1, 2, 3]


# -- transforms ------------------------------------------------------------

def test_flat_map_limit_sample():
    ds = rd.range(10, parallelism=2).flat_map(
        lambda r: [{"v": r["id"]}, {"v": r["id"] + 100}])
    assert ds.count() == 20
    assert ds.limit(3).count() == 3
    sampled = rd.range(1000).random_sample(0.1, seed=0)
    assert 50 < sampled.count() < 200


def test_select_drop_columns():
    ds = rd.from_numpy({"a": np.arange(4), "b": np.arange(4),
                        "c": np.arange(4)})
    assert set(ds.select_columns(["a", "b"]).schema()) == {"a", "b"}
    assert set(ds.drop_columns(["a"]).schema()) == {"b", "c"}


def test_zip_and_split_at_indices():
    a = rd.from_numpy({"x": np.arange(6)})
    b = rd.from_numpy({"y": np.arange(6) * 2})
    z = a.zip(b)
    assert set(z.schema()) == {"x", "y"}
    parts = z.split_at_indices([2, 4])
    assert [p.count() for p in parts] == [2, 2, 2]


def test_train_test_split():
    tr, te = rd.range(100).train_test_split(test_size=0.2, shuffle=True,
                                            seed=0)
    assert tr.count() == 80 and te.count() == 20
    ids = {r["id"] for r in tr.take_all()} | {r["id"] for r in te.take_all()}
    assert len(ids) == 100


# -- groupby / aggregates --------------------------------------------------

def test_groupby_aggregates():
    ds = rd.from_numpy({"k": np.array([0, 1, 0, 1, 0]),
                        "v": np.array([1.0, 2.0, 3.0, 4.0, 5.0])})
    g = ds.groupby("k")
    s = {r["k"]: r["sum(v)"] for r in g.sum("v").take_all()}
    assert s == {0: 9.0, 1: 6.0}
    c = {r["k"]: r["count()"] for r in g.count().take_all()}
    assert c == {0: 3, 1: 2}
    m = {r["k"]: r["mean(v)"] for r in g.mean("v").take_all()}
    assert m == {0: 3.0, 1: 3.0}
    mn = {r["k"]: r["min(v)"] for r in g.min("v").take_all()}
    assert mn == {0: 1.0, 1: 2.0}
    st = {r["k"]: r["std(v)"] for r in g.std("v").take_all()}
    np.testing.assert_allclose(st[0], np.std([1, 3, 5], ddof=1), rtol=1e-6)


def test_groupby_multiblock_merge():
    # groups spanning blocks must merge partials
    ds = rd.from_numpy({"k": np.arange(20) % 3,
                        "v": np.ones(20)}, parallelism=5)
    c = {r["k"]: r["count()"] for r in ds.groupby("k").count().take_all()}
    assert sum(c.values()) == 20 and set(c) == {0, 1, 2}


def test_map_groups():
    ds = rd.from_numpy({"k": np.array([0, 0, 1]),
                        "v": np.array([1.0, 2.0, 3.0])})
    out = ds.groupby("k").map_groups(
        lambda g: {"k": g["k"][:1], "vmax": g["v"].max(keepdims=True)})
    rows = {r["k"]: r["vmax"] for r in out.take_all()}
    assert rows == {0: 2.0, 1: 3.0}


def test_global_aggregates():
    ds = rd.from_numpy({"v": np.arange(10, dtype=np.float64)})
    assert ds.sum("v") == 45.0
    assert ds.mean("v") == 4.5
    assert ds.min("v") == 0.0 and ds.max("v") == 9.0
    assert ds.unique("v")[:3] == [0.0, 1.0, 2.0]


# -- pipeline --------------------------------------------------------------

def test_dataset_pipeline_window_repeat():
    ds = rd.range(16, parallelism=4)
    pipe = ds.window(blocks_per_window=2)
    assert len(pipe) == 2
    assert pipe.count() == 16
    pipe2 = ds.repeat(3)
    assert pipe2.count() == 48
    batches = list(ds.repeat(2).map_batches(
        lambda b: {"id": b["id"] * 2}).iter_batches(batch_size=8))
    assert sum(len(b["id"]) for b in batches) == 32
    assert all((b["id"] % 2 == 0).all() for b in batches)


def test_pipeline_shuffle_each_window():
    ds = rd.range(8, parallelism=2)
    pipe = ds.window(blocks_per_window=1).random_shuffle_each_window(seed=0)
    assert pipe.count() == 8


# -- new preprocessors -----------------------------------------------------

def test_one_hot_and_imputer():
    from ray_tpu.data import OneHotEncoder, SimpleImputer
    ds = rd.from_numpy({"c": np.array(["a", "b", "a", "c"]),
                        "x": np.array([1.0, np.nan, 3.0, np.nan])})
    oh = OneHotEncoder(["c"]).fit_transform(ds)
    row = oh.take(2)
    np.testing.assert_array_equal(row[0]["c"], [1, 0, 0])
    np.testing.assert_array_equal(row[1]["c"], [0, 1, 0])
    im = SimpleImputer(["x"], strategy="mean").fit_transform(ds)
    xs = np.array([r["x"] for r in im.take_all()])
    np.testing.assert_allclose(xs, [1.0, 2.0, 3.0, 2.0])


def test_normalizer_and_robust_scaler():
    from ray_tpu.data import Normalizer, RobustScaler
    ds = rd.from_numpy({"a": np.array([3.0, 0.0]),
                        "b": np.array([4.0, 5.0])})
    out = Normalizer(["a", "b"]).fit_transform(ds).take_all()
    np.testing.assert_allclose([out[0]["a"], out[0]["b"]], [0.6, 0.8])
    ds2 = rd.from_numpy({"v": np.arange(101, dtype=np.float64)})
    rs = RobustScaler(["v"]).fit_transform(ds2)
    vs = np.array([r["v"] for r in rs.take_all()])
    np.testing.assert_allclose(np.median(vs), 0.0, atol=1e-9)


def test_map_batches_as_actors(rt_init):
    ds = rd.range(20, parallelism=4).map_batches(
        lambda b: {"id": b["id"] + 1})
    out = ds.materialize(parallelism="actors")
    assert out.count() == 20
    assert sorted(r["id"] for r in out.take_all()) == list(range(1, 21))


# -- review regression tests -----------------------------------------------

def test_write_json_vector_columns(tmp_path):
    ds = rd.from_numpy(np.arange(12).reshape(6, 2))
    ds.write_json(str(tmp_path / "v"))
    back = rd.read_json(str(tmp_path / "v"))
    assert back.count() == 6


def test_read_json_heterogeneous_rows(tmp_path):
    p = tmp_path / "h.json"
    p.write_text('{"a": 1}\n{"a": 2, "b": 3}\n')
    ds = rd.read_json(str(p))
    rows = ds.take_all()
    assert rows[0]["b"] is None and rows[1]["b"] == 3


def test_random_sample_decorrelated_blocks():
    ds = rd.range(1000, parallelism=10).random_sample(0.3, seed=7)
    ids = np.array([r["id"] for r in ds.take_all()])
    # per-block positions must differ: the mod-100 residues should not
    # collapse to a handful of values
    assert len(set(ids % 100)) > 10


def test_imputer_constant_requires_fill():
    from ray_tpu.data import SimpleImputer
    with pytest.raises(ValueError):
        SimpleImputer(["x"], strategy="constant")
    ds = rd.from_numpy({"x": np.array([1.0, np.nan])})
    out = SimpleImputer(["x"], strategy="constant",
                        fill_value=9.0).fit_transform(ds)
    assert out.take_all()[1]["x"] == 9.0


def test_infinite_pipeline_count_raises():
    pipe = rd.range(4).repeat()
    with pytest.raises(TypeError):
        pipe.count()
    assert len(pipe.take(6)) == 6  # take stays bounded


def test_aggregate_finalize_wired():
    from ray_tpu.data import AggregateFn
    ds = rd.from_numpy({"k": np.array([0, 0, 1]),
                        "v": np.array([2.0, 4.0, 8.0])})
    halfsum = AggregateFn("halfsum(v)", lambda v: v.sum(), np.add,
                          finalize=lambda x: x / 2)
    out = {r["k"]: r["halfsum(v)"]
           for r in ds.groupby("k").aggregate((halfsum, "v")).take_all()}
    assert out == {0: 3.0, 1: 4.0}


def test_read_json_ragged_lists(tmp_path):
    p = tmp_path / "r.json"
    p.write_text('{"a": [1, 2]}\n{"a": [1, 2, 3]}\n')
    rows = rd.read_json(str(p)).take_all()
    assert rows[0]["a"] == [1, 2] and rows[1]["a"] == [1, 2, 3]


def test_zip_suffix_probe():
    a = rd.from_numpy({"y": np.arange(3), "y_1": np.arange(3) * 10})
    b = rd.from_numpy({"y": np.arange(3) * 100})
    z = a.zip(b)
    assert set(z.schema()) == {"y", "y_1", "y_2"}
    r = z.take(1)[0]
    assert r["y_1"] == 0 and r["y_2"] == 0


def test_normalizer_stateless_transform():
    from ray_tpu.data import Normalizer
    ds = rd.from_numpy({"a": np.array([3.0]), "b": np.array([4.0])})
    out = Normalizer(["a", "b"]).transform(ds).take(1)[0]  # no fit()
    np.testing.assert_allclose([out["a"], out["b"]], [0.6, 0.8])


# -- arrow blocks ----------------------------------------------------------

def test_arrow_block_roundtrip(tmp_path):
    import pyarrow as pa
    from ray_tpu.data import Dataset

    t = pa.table({"a": list(range(10)), "b": [f"s{i}" for i in range(10)]})
    ds = Dataset.from_arrow(t)
    assert ds.count() == 10
    assert ds.sum("a") == 45
    out = ds.to_arrow()
    assert out.column_names == ["a", "b"] and out.num_rows == 10

    # stages over arrow blocks: filter/map/select keep working
    small = (ds.filter(lambda r: r["a"] % 2 == 0)
             .select_columns(["a"]))
    assert sorted(r["a"] for r in small.take_all()) == [0, 2, 4, 6, 8]


def test_parquet_arrow_blocks(tmp_path):
    import numpy as np
    import pyarrow as pa
    from ray_tpu.data import Dataset
    from ray_tpu.data import block as B

    ds = Dataset.from_numpy({"x": np.arange(100.0),
                             "y": np.arange(100) % 5})
    paths = ds.write_parquet(str(tmp_path))
    assert len(paths) >= 1

    back = Dataset.read_parquet(str(tmp_path))
    # default block format is arrow: zero-copy tables
    assert all(B.is_arrow(b) for b in back._resolve_blocks())
    assert back.count() == 100
    assert back.sum("x") == 4950.0
    # batches still come out as numpy column dicts for the device path
    batch = next(back.iter_batches(batch_size=32))
    assert isinstance(batch["x"], np.ndarray) and len(batch["x"]) == 32


def test_map_batches_arrow_format():
    import pyarrow as pa
    from ray_tpu.data import Dataset

    ds = Dataset.range(20)

    def arrow_fn(t):
        assert isinstance(t, pa.Table)   # fn sees a Table
        return t.append_column("double", pa.array(
            [v * 2 for v in t["id"].to_pylist()]))

    out = ds.map_batches(arrow_fn, batch_format="arrow")
    assert out.sum("double") == 2 * sum(range(20))


# -- streaming executor ----------------------------------------------------

def test_streaming_executor_backpressure(rt_init):
    import numpy as np
    from ray_tpu.data import Dataset
    from ray_tpu.data.execution import (StreamingExecutor,
                                        build_operator_chain)

    ds = Dataset.from_numpy({"x": np.arange(64.0)}, parallelism=8)
    ds2 = ds.map_batches(lambda b: {"x": b["x"] * 3})
    ops = build_operator_chain(ds2._stages, max_in_flight=2)
    ex = StreamingExecutor(ops)
    out = list(ex.execute(ds2._resolve_blocks()))
    assert sum(b["x"].sum() for b in out) == 3 * np.arange(64.0).sum()
    stats = ex.stats()
    assert stats[0]["outputs"] == 8
    # backpressure: never more than max_in_flight submitted at once
    assert stats[0]["peak_in_flight"] <= 2


def test_iter_batches_streaming_matches_inline(rt_init):
    import numpy as np
    from ray_tpu.data import Dataset

    ds = (Dataset.from_numpy({"x": np.arange(40.0)}, parallelism=5)
          .map_batches(lambda b: {"x": b["x"] + 1}))
    inline = [b["x"] for b in ds.iter_batches(batch_size=8)]
    streamed = [b["x"] for b in ds.iter_batches(batch_size=8,
                                                parallelism="streaming",
                                                max_in_flight=2)]
    assert len(inline) == len(streamed) == 5
    for a, b in zip(inline, streamed):
        np.testing.assert_array_equal(a, b)


def test_materialize_streaming(rt_init):
    import numpy as np
    from ray_tpu.data import Dataset

    ds = (Dataset.from_numpy({"x": np.arange(30.0)}, parallelism=6)
          .map_batches(lambda b: {"x": b["x"] ** 2}))
    out = ds.materialize(parallelism="streaming")
    assert out.sum("x") == float((np.arange(30.0) ** 2).sum())
