"""Data layer tests (reference analogue: python/ray/data/tests —
test_dataset.py map/filter/shuffle/split, preprocessor tests)."""
import numpy as np
import pytest

from ray_tpu import data as rd
from ray_tpu.data import (BatchMapper, Chain, Concatenator, LabelEncoder,
                          StandardScaler)


def test_range_count_take():
    ds = rd.range(100, parallelism=7)
    assert ds.count() == 100
    rows = ds.take(5)
    assert [r["id"] for r in rows] == [0, 1, 2, 3, 4]


def test_from_items_rows():
    ds = rd.from_items([{"a": i, "b": i * 2} for i in range(10)])
    assert ds.count() == 10
    assert ds.take(1)[0]["b"] == 0
    assert set(ds.schema().keys()) == {"a", "b"}


def test_map_batches_and_filter():
    ds = (rd.range(50)
          .map_batches(lambda b: {"id": b["id"], "sq": b["id"] ** 2})
          .filter(lambda r: r["sq"] % 2 == 0))
    rows = ds.take_all()
    assert all(r["sq"] == r["id"] ** 2 for r in rows)
    assert all(r["sq"] % 2 == 0 for r in rows)


def test_repartition_shuffle_sort():
    ds = rd.range(40, parallelism=4).repartition(8)
    assert ds.stats()["num_blocks"] == 8
    sh = rd.range(40).random_shuffle(seed=0)
    ids = [r["id"] for r in sh.take_all()]
    assert sorted(ids) == list(range(40))
    assert ids != list(range(40))
    st = sh.sort("id")
    assert [r["id"] for r in st.take(3)] == [0, 1, 2]


def test_split_even():
    parts = rd.range(10).split(3)
    counts = [p.count() for p in parts]
    assert sum(counts) == 10
    assert counts[:2] == [3, 3]


def test_iter_batches_sizes():
    ds = rd.range(25, parallelism=3)
    sizes = [len(b["id"]) for b in ds.iter_batches(batch_size=10)]
    assert sizes == [10, 10, 5]
    sizes = [len(b["id"])
             for b in ds.iter_batches(batch_size=10, drop_last=True)]
    assert sizes == [10, 10]


def test_iter_batches_sharded_mesh():
    import jax
    from ray_tpu.parallel.mesh import create_mesh
    mesh = create_mesh({"dp": 4}, devices=jax.devices("cpu")[:4])
    ds = rd.from_numpy({"x": np.arange(64, dtype=np.float32)})
    batches = list(ds.iter_batches_sharded(mesh, batch_size=16))
    assert len(batches) == 4
    x = batches[0]["x"]
    assert isinstance(x, jax.Array)
    assert x.sharding.num_devices == 4


def test_csv_parquet_roundtrip(tmp_path):
    import pandas as pd
    p = tmp_path / "t.csv"
    pd.DataFrame({"a": [1, 2, 3], "b": [4.0, 5.0, 6.0]}).to_csv(
        p, index=False)
    ds = rd.read_csv(str(p))
    assert ds.count() == 3
    paths = ds.write_parquet(str(tmp_path / "pq"))
    ds2 = rd.read_parquet(paths)
    assert ds2.count() == 3
    assert ds2.take(1)[0]["b"] == 4.0


def test_preprocessors():
    ds = rd.from_numpy({"x": np.arange(10, dtype=np.float64),
                        "label": np.array(list("abbaabbaba"))})
    sc = StandardScaler(["x"])
    out = sc.fit_transform(ds)
    xs = np.array([r["x"] for r in out.take_all()])
    assert abs(xs.mean()) < 1e-9
    le = LabelEncoder("label")
    enc = le.fit_transform(ds)
    labs = {r["label"] for r in enc.take_all()}
    assert labs == {0, 1}


def test_chain_and_concatenator():
    ds = rd.from_numpy({"x": np.arange(8, dtype=np.float64),
                        "y": np.arange(8, dtype=np.float64) * 3})
    chain = Chain(StandardScaler(["x", "y"]),
                  Concatenator(["x", "y"], "features"))
    out = chain.fit_transform(ds)
    row = out.take(1)[0]
    assert row["features"].shape == (2,)


def test_map_batches_as_tasks(rt_init):
    ds = rd.range(20, parallelism=4).map_batches(
        lambda b: {"id": b["id"] + 1})
    assert ds.materialize(parallelism="tasks").count() == 20
