"""Util tests (reference analogue: python/ray/tests/test_actor_pool.py,
test_queue.py)."""
import pytest

import ray_tpu
from ray_tpu.util import ActorPool, Empty, Queue


@ray_tpu.remote
class Doubler:
    def work(self, x):
        return 2 * x


def test_actor_pool_map(rt_init):
    pool = ActorPool([Doubler.remote() for _ in range(2)])
    out = list(pool.map(lambda a, v: a.work.remote(v), range(6)))
    assert out == [0, 2, 4, 6, 8, 10]


def test_actor_pool_unordered(rt_init):
    pool = ActorPool([Doubler.remote() for _ in range(2)])
    out = sorted(pool.map_unordered(lambda a, v: a.work.remote(v), range(5)))
    assert out == [0, 2, 4, 6, 8]


def test_queue_fifo(rt_init):
    q = Queue()
    for i in range(4):
        q.put(i)
    assert q.qsize() == 4
    assert [q.get() for _ in range(4)] == [0, 1, 2, 3]
    assert q.empty()
    with pytest.raises(Empty):
        q.get_nowait()


def test_queue_shared_by_name(rt_init):
    q1 = Queue(name="shared_q")
    q2 = Queue(name="shared_q")
    q1.put("hello")
    assert q2.get(timeout=30) == "hello"
