"""Device-resident (HBM) object store entries.

Reference CONTRAST (not parity): plasma is host-only
(src/ray/object_manager/plasma/store.h:55) — every put of an accelerator
tensor crosses to host RAM.  Here put() of jax values keeps the device
buffers in the owning process (core/device_objects.py); these tests pin
the zero-copy same-process path, materialize-on-demand for other
processes, budget spill, free, and owner-death semantics.
"""

from __future__ import annotations

import os
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import ray_tpu


@pytest.fixture
def rt():
    r = ray_tpu.init(num_cpus=2, num_tpus=0)
    yield ray_tpu
    ray_tpu.shutdown()


def _weights(n=4, sz=256):
    key = jax.random.PRNGKey(0)
    return {f"layer{i}": jnp.asarray(
        jax.random.normal(jax.random.fold_in(key, i), (sz, sz)))
        for i in range(n)}


def test_same_process_get_is_zero_copy(rt):
    """get() in the owner process returns the SAME jax.Array objects —
    the strongest possible no-host-bounce proof (no np.asarray, no
    device_get, no serialize of the buffers can have happened)."""
    w = _weights()
    ref = ray_tpu.put(w)
    got = ray_tpu.get(ref, timeout=60)
    assert got is not w                      # fresh container (immutable)
    for k in w:
        assert got[k] is w[k], f"leaf {k} was copied"
    # the entry is device-resident, not in the host store
    from ray_tpu.core.runtime import get_runtime
    assert ref.id.binary() in get_runtime().client.device_table


def test_put_skips_host_serialization(rt):
    """The put path must not materialize device buffers to host bytes:
    a put whose leaves total ~64MB stores only a tiny descriptor."""
    big = jnp.ones((4096, 4096), jnp.float32)           # 64 MB
    from ray_tpu.core.runtime import get_runtime
    stats0 = get_runtime().client.request({"t": "object_stats"})["stats"]
    t0 = time.perf_counter()
    ref = ray_tpu.put({"w": big})
    dt = time.perf_counter() - t0
    stats1 = get_runtime().client.request({"t": "object_stats"})["stats"]
    # nothing landed in the shm store (descriptor goes inline)
    assert stats1.get("bytes_used", 0) == stats0.get("bytes_used", 0)
    got = ray_tpu.get(ref, timeout=60)
    assert got["w"] is big
    # not a strict perf assertion (1-core CI box), but a 64MB host copy
    # through pickle takes far longer than a descriptor put
    assert dt < 2.0, f"device put took {dt:.2f}s — did it host-copy?"


def test_cross_process_get_materializes(rt):
    """A different process pulling the ref triggers exactly one owner-
    side spill to host, after which the value reads normally."""
    w = _weights(n=2, sz=64)
    ref = ray_tpu.put(w)

    @ray_tpu.remote
    def read(r):
        import numpy as _np
        return {k: float(_np.asarray(v).sum()) for k, v in r.items()}

    out = ray_tpu.get(read.remote(ref), timeout=120)
    for k in w:
        assert out[k] == pytest.approx(float(jnp.sum(w[k])), rel=1e-5)
    # after materialization the owner dropped its HBM entry
    from ray_tpu.core.runtime import get_runtime
    deadline = time.time() + 30
    while time.time() < deadline and \
            ref.id.binary() in get_runtime().client.device_table:
        time.sleep(0.05)
    assert ref.id.binary() not in get_runtime().client.device_table


def test_free_drops_device_entry(rt):
    w = _weights(n=1, sz=32)
    ref = ray_tpu.put(w)
    from ray_tpu.core.runtime import get_runtime
    assert ref.id.binary() in get_runtime().client.device_table
    ray_tpu.free([ref])
    deadline = time.time() + 30
    while time.time() < deadline and \
            ref.id.binary() in get_runtime().client.device_table:
        time.sleep(0.05)
    assert ref.id.binary() not in get_runtime().client.device_table
    with pytest.raises(Exception):
        ray_tpu.get(ref, timeout=1)


def test_budget_spills_oldest_to_host(rt, monkeypatch):
    """Exceeding the per-process HBM budget spills the OLDEST entries to
    the host store — they stay readable, newer entries stay device-side."""
    from ray_tpu.core.runtime import get_runtime
    client = get_runtime().client
    client.device_table.budget_bytes = 4 * (1 << 20)    # 4 MB

    a = jnp.ones((1024, 1024), jnp.float32)             # 4 MB each
    b = a + 1
    ref_a = ray_tpu.put({"x": a})
    ref_b = ray_tpu.put({"x": b})
    # oldest (a) must leave the device table to honor the budget
    deadline = time.time() + 30
    while time.time() < deadline and \
            ref_a.id.binary() in client.device_table:
        time.sleep(0.05)
    assert ref_a.id.binary() not in client.device_table
    assert ref_b.id.binary() in client.device_table
    got_a = ray_tpu.get(ref_a, timeout=60)              # now host-backed
    got_b = ray_tpu.get(ref_b, timeout=60)              # still zero-copy
    assert np.allclose(np.asarray(got_a["x"]), 1.0)
    assert got_b["x"] is b
    client.device_table.budget_bytes = None


def test_owner_death_loses_device_object(rt):
    """A put()-only device object (no lineage) dies with its owner
    process and surfaces as an error, not a hang."""
    @ray_tpu.remote
    def make():
        import jax.numpy as _jnp
        r = ray_tpu.put({"w": _jnp.ones((64, 64))})
        return r, os.getpid()

    inner, pid = ray_tpu.get(make.remote(), timeout=120)
    os.kill(pid, 9)
    with pytest.raises(Exception, match="died|freed|lost"):
        ray_tpu.get(inner, timeout=60)


def test_weight_sync_put_is_instant_device_side(rt):
    """The RLlib sync_weights shape: put big params, hand the ref to N
    consumers — the put itself must not host-copy (device descriptor
    only), consumers share ONE materialization."""
    w = {f"l{i}": jnp.ones((512, 512), jnp.float32) for i in range(8)}

    t0 = time.perf_counter()
    ref = ray_tpu.put(w)
    put_dt = time.perf_counter() - t0
    assert put_dt < 1.0, f"weight put took {put_dt:.2f}s"

    @ray_tpu.remote
    def consume(r):
        import numpy as _np
        return sum(float(_np.asarray(v).sum()) for v in r.values())

    outs = ray_tpu.get([consume.remote(ref) for _ in range(2)], timeout=180)
    want = sum(float(jnp.sum(v)) for v in w.values())
    assert all(o == pytest.approx(want, rel=1e-5) for o in outs)
