"""RLModule/Learner/LearnerGroup tests (reference test model:
rllib/core/rl_module/tests, rllib/core/rl_trainer/tests)."""

import jax
import numpy as np
import pytest

from ray_tpu.rllib.rl_module import (DiscretePGModule, Learner,
                                     LearnerGroup, MultiRLModule)


def _pg_batch(rng, n=64, obs_dim=4, num_actions=2):
    return {
        "obs": rng.normal(size=(n, obs_dim)).astype(np.float32),
        "actions": rng.integers(0, num_actions, n).astype(np.int64),
        "advantages": rng.normal(size=n).astype(np.float32),
        "value_targets": rng.normal(size=n).astype(np.float32),
    }


def test_module_forward_contracts():
    m = DiscretePGModule(obs_dim=4, num_actions=3)
    params = m.init_params(jax.random.PRNGKey(0))
    batch = {"obs": np.zeros((5, 4), np.float32)}
    inf = m.forward_inference(params, batch)
    assert inf["actions"].shape == (5,) and inf["logits"].shape == (5, 3)
    exp = m.forward_exploration(
        params, {**batch, "rng": jax.random.PRNGKey(1)})
    assert exp["logp"].shape == (5,)


def test_learner_reduces_loss():
    m = DiscretePGModule(obs_dim=4, num_actions=2, ent_coeff=0.0)
    learner = Learner(m, lr=0.05, seed=0)
    rng = np.random.default_rng(0)
    batch = _pg_batch(rng)
    first = learner.update(batch)["loss"]
    for _ in range(20):
        last = learner.update(batch)["loss"]
    assert last < first


def test_multi_rl_module():
    mm = MultiRLModule({
        "p0": DiscretePGModule(obs_dim=4, num_actions=2),
        "p1": DiscretePGModule(obs_dim=4, num_actions=2)})
    params = mm.init_params(jax.random.PRNGKey(0))
    assert set(params) == {"p0", "p1"}
    rng = np.random.default_rng(1)
    batch = {"p0": _pg_batch(rng), "p1": _pg_batch(rng)}
    loss = mm.loss(jax.tree.map(lambda x: x, params), batch)
    assert np.isfinite(float(loss))
    learner = Learner(mm, lr=0.05)
    assert np.isfinite(learner.update(batch)["loss"])


def test_learner_group_inline():
    group = LearnerGroup(
        lambda: DiscretePGModule(obs_dim=4, num_actions=2), 0, lr=0.05)
    rng = np.random.default_rng(2)
    out = group.update(_pg_batch(rng))
    assert np.isfinite(out["loss"])
    assert group.num_learners == 1


def test_multi_module_exploration_delegates():
    mm = MultiRLModule({
        "p0": DiscretePGModule(obs_dim=4, num_actions=2)})
    params = mm.init_params(jax.random.PRNGKey(0))
    out = mm.forward_exploration(
        params, {"p0": {"obs": np.zeros((3, 4), np.float32),
                        "rng": jax.random.PRNGKey(1)}})
    assert "logp" in out["p0"]     # sampled, not greedy fallback


def test_learner_group_tiny_batch_no_nan(rt_init):
    group = LearnerGroup(
        lambda: DiscretePGModule(obs_dim=4, num_actions=2), 2, lr=0.05)
    rng = np.random.default_rng(5)
    out = group.update(_pg_batch(rng, n=1))  # rows < num_learners
    assert np.isfinite(out["loss"])
    w = group.get_weights()
    assert all(np.isfinite(l).all() for l in jax.tree.leaves(w))
    group.stop()


def test_learner_group_distributed(rt_init):
    group = LearnerGroup(
        lambda: DiscretePGModule(obs_dim=4, num_actions=2, ent_coeff=0.0),
        2, lr=0.05, seed=3)
    rng = np.random.default_rng(3)
    batch = _pg_batch(rng, n=128)
    first = group.update(batch)["loss"]
    for _ in range(5):
        last = group.update(batch)["loss"]
    assert last < first     # sync-DP averaging still learns
    w = group.get_weights()
    assert any(leaf.size for leaf in jax.tree.leaves(w))
    group.stop()
