"""Native C++ shm arena store: unit + end-to-end integration.

The C++ unit tests live in native/tests/store_test.cc (run via
`make -C native test`); these cover the ctypes wrapper and the runtime
integration (puts/gets route through the arena, eviction-driven spill).
"""

import os

import numpy as np
import pytest

from ray_tpu import native
from ray_tpu.core.ids import ObjectID, TaskID

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native toolchain unavailable")


def _oid(n: int) -> ObjectID:
    return ObjectID(n.to_bytes(4, "little") * 7)


@pytest.fixture
def arena():
    from ray_tpu.native.store import NativeArena
    name = f"rt_pytest_{os.getpid()}"
    a = NativeArena(name, capacity=1 << 20, create=True)
    yield a
    a.destroy()


class TestNativeArena:
    def test_create_seal_get(self, arena):
        oid = _oid(1).binary()
        buf = arena.create(oid, 100)
        buf[:5] = b"hello"
        del buf
        # unsealed objects are not gettable
        assert arena.get(oid) is None
        arena.seal(oid)
        arr = arena.get(oid)
        assert arr is not None and bytes(arr[:5]) == b"hello"
        assert arena.refcount(oid) == 1
        del arr
        import gc
        gc.collect()
        assert arena.refcount(oid) == 0

    def test_zero_copy(self, arena):
        oid = _oid(2).binary()
        data = np.arange(1000, dtype=np.float64)
        buf = arena.create(oid, data.nbytes)
        np.frombuffer(buf, dtype=np.float64)[:] = data
        del buf
        arena.seal(oid)
        arr = arena.get(oid)
        view = np.frombuffer(arr, dtype=np.float64)
        np.testing.assert_array_equal(view, data)
        # view keeps a native ref → deletion refused, no reuse-after-free
        assert not arena.delete(oid)
        del view, arr
        import gc
        gc.collect()
        assert arena.delete(oid)

    def test_oom_and_reuse(self, arena):
        from ray_tpu.native.store import NativeStoreFull
        # heap = capacity + slack (~2.2MB for a 1MB arena): fill it until
        # allocation fails, then freeing must make space reusable
        made = []
        with pytest.raises(NativeStoreFull):
            for i in range(3, 10):
                buf = arena.create(_oid(i).binary(), 900_000)
                del buf
                arena.seal(_oid(i).binary())
                made.append(i)
        assert made, "expected at least one allocation to fit"
        assert arena.delete(_oid(made[0]).binary())
        buf = arena.create(_oid(99).binary(), 900_000)
        assert len(buf) == 900_000

    def test_evict_candidates_lru(self, arena):
        for i in range(5, 9):
            arena.create(_oid(i).binary(), 1000)
            arena.seal(_oid(i).binary())
        # refresh 5 so 6 is the LRU
        arr = arena.get(_oid(5).binary())
        del arr
        cands = arena.evict_candidates(1500)
        assert cands[0] == _oid(6).binary()
        assert len(cands) == 2

    def test_multiprocess_visibility(self, arena):
        import subprocess
        import sys

        oid = _oid(10).binary()
        code = (
            "import sys\n"
            "from ray_tpu.native.store import NativeArena\n"
            "a = NativeArena(sys.argv[1])\n"
            "oid = bytes.fromhex(sys.argv[2])\n"
            "buf = a.create(oid, 64)\n"
            "buf[:2] = b'mp'\n"
            "del buf\n"
            "a.seal(oid)\n"
            "a.detach()\n"
            "print('ok')\n")
        out = subprocess.run(
            [sys.executable, "-c", code, arena._name.decode(), oid.hex()],
            capture_output=True, text=True, timeout=60,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        assert out.returncode == 0 and "ok" in out.stdout, out.stderr
        arr = arena.get(oid)
        assert bytes(arr[:2]) == b"mp"


class TestRuntimeIntegration:
    def test_put_get_through_arena(self, rt_init):
        rt = rt_init
        # large enough to bypass the inline path
        x = np.random.rand(512, 512)
        ref = rt.put(x)
        out = rt.get(ref)
        np.testing.assert_array_equal(out, x)
        stats = rt.object_store_stats()
        assert stats.get("native"), "expected the native arena backend"

    def test_task_large_args_and_returns(self, rt_init):
        rt = rt_init

        @rt.remote
        def double(a):
            return a * 2

        x = np.ones((256, 1024))
        refs = [double.remote(x) for _ in range(4)]
        for out in rt.get(refs):
            np.testing.assert_array_equal(out, x * 2)

    def test_spill_under_pressure(self, rt_init):
        rt = rt_init
        # default store budget in tests is small enough to force spill
        refs = [rt.put(np.random.rand(1 << 17)) for _ in range(50)]
        # every object still retrievable (restored from spill if needed)
        for r in refs[:5] + refs[-5:]:
            assert rt.get(r).shape == (1 << 17,)
