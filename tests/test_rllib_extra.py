"""Tests for round-2 RLlib breadth: PG, ES/ARS, bandits, CQL, DDPG/TD3,
APEX-DQN, connectors, policy server (reference test models:
rllib/algorithms/*/tests/, rllib/tests/test_connectors.py,
rllib/tests/test_policy_client_server_setup.py)."""

import numpy as np
import pytest

from ray_tpu.rllib.bandit import BanditConfig, LinearBanditEnv
from ray_tpu.rllib.connectors import (ClipActions, ClipReward,
                                      ConnectorPipeline, FrameStack,
                                      MeanStdFilter, UnsquashActions)
from ray_tpu.rllib.ddpg import DDPGConfig, TD3Config
from ray_tpu.rllib.env import Pendulum, VectorEnv
from ray_tpu.rllib.es import ARSConfig, ESConfig
from ray_tpu.rllib.pg import PGConfig
from ray_tpu.rllib.policy_server import PolicyClient, PolicyServerInput


def test_pendulum_env_contract():
    env = Pendulum(seed=0)
    obs = env.reset()
    assert obs.shape == (3,)
    obs, rew, done, _ = env.step(np.array([0.5]))
    assert obs.shape == (3,) and rew <= 0.0 and not done
    vec = VectorEnv("Pendulum-v1", 2, seed=0)
    assert vec.action_dim == 1 and vec.num_actions is None
    vec.reset()
    o, r, d = vec.step(np.zeros((2, 1), np.float32))
    assert o.shape == (2, 3)


@pytest.mark.slow
def test_pg_learns_cartpole():
    algo = PGConfig(env="CartPole-v1", num_rollout_workers=0,
                    num_envs_per_worker=8, rollout_length=64,
                    train_batch_size=2048, lr=4e-3, seed=0).build()
    best = 0.0
    for _ in range(40):
        best = max(best,
                   algo.train().get("episode_reward_mean", 0.0))
        if best > 90:
            break
    # vanilla PG oscillates (no trust region); track the best window —
    # random CartPole sits near 20, so 90 demonstrates real learning
    assert best > 90, f"PG failed to learn: best {best}"


def test_es_improves_cartpole():
    algo = ESConfig(env="CartPole-v1", pop_size=12, sigma=0.1,
                    step_size=0.05, max_episode_steps=200,
                    seed=0).build()
    first = algo.train()["pop_return_mean"]
    best = first
    for _ in range(12):
        best = max(best, algo.train()["pop_return_mean"])
    assert best > first + 10, f"ES no improvement: {first} -> {best}"


def test_ars_runs_and_checkpoints(tmp_path):
    algo = ARSConfig(env="CartPole-v1", pop_size=8, top_directions=4,
                     max_episode_steps=100, seed=0).build()
    r1 = algo.train()
    assert r1["steps_this_iter"] > 0
    ck = algo.save_checkpoint()
    theta_before = np.asarray(algo.theta).copy()
    algo.train()
    algo.load_checkpoint(ck)
    np.testing.assert_allclose(np.asarray(algo.theta), theta_before)


def test_linucb_regret_shrinks():
    cfg = BanditConfig(env=lambda: LinearBanditEnv(seed=1),
                       exploration="ucb", steps_per_iter=256, seed=0)
    algo = cfg.build()
    first = algo.train()["mean_regret"]
    last = first
    for _ in range(4):
        last = algo.train()["mean_regret"]
    assert last < first * 0.6, f"LinUCB regret {first} -> {last}"


def test_lints_learns():
    cfg = BanditConfig(env=lambda: LinearBanditEnv(seed=2),
                       exploration="ts", steps_per_iter=256, seed=0)
    algo = cfg.build()
    first = algo.train()["mean_regret"]
    last = first
    for _ in range(4):
        last = algo.train()["mean_regret"]
    assert last < first, f"LinTS regret {first} -> {last}"


def _write_offline_cartpole(path, n_steps=3000):
    """Behavior data from a random policy, (s, a, r, s') columns."""
    from ray_tpu.rllib.env import CartPole
    from ray_tpu.rllib.offline import JsonWriter
    from ray_tpu.rllib.sample_batch import SampleBatch
    rng = np.random.default_rng(0)
    env = CartPole(seed=0)
    obs = env.reset()
    rows = {k: [] for k in ("obs", "actions", "rewards", "dones",
                            "next_obs")}
    for _ in range(n_steps):
        a = int(rng.integers(0, 2))
        nxt, r, done, _ = env.step(a)
        rows["obs"].append(obs)
        rows["actions"].append(a)
        rows["rewards"].append(r)
        rows["dones"].append(float(done))
        rows["next_obs"].append(nxt)
        obs = env.reset() if done else nxt
    w = JsonWriter(str(path))
    w.write(SampleBatch({
        "obs": np.stack(rows["obs"]).astype(np.float32),
        "actions": np.asarray(rows["actions"], np.int64),
        "rewards": np.asarray(rows["rewards"], np.float32),
        "dones": np.asarray(rows["dones"], np.float32),
        "next_obs": np.stack(rows["next_obs"]).astype(np.float32)}))
    w.close()


def test_cql_trains_offline(tmp_path):
    from ray_tpu.rllib.cql import CQLConfig
    _write_offline_cartpole(tmp_path / "data")
    algo = CQLConfig(input_path=str(tmp_path / "data"), cql_alpha=1.0,
                     batch_size=128, grad_steps_per_iter=50,
                     seed=0).build()
    r1 = algo.train()
    r2 = algo.train()
    assert np.isfinite(r2["loss"])
    # conservative gap should shrink as Q-values get pushed down
    assert r2["cql_gap"] < r1["cql_gap"] + 1.0
    a = algo.compute_action(np.zeros(4, np.float32))
    assert a in (0, 1)


@pytest.mark.slow
def test_td3_solves_pendulum():
    # measured trajectory with these hyperparams (seed 0): -1331 at
    # iter 3 -> -305 at iter 12 -> -204 at iter 15 (near-optimal ~-150)
    algo = TD3Config(env="Pendulum-v1", num_envs_per_worker=4,
                     rollout_length=128, learning_starts=500,
                     batch_size=128, train_intensity=1.0,
                     actor_lr=3e-3, critic_lr=3e-3, tau=0.01,
                     exploration_noise=0.15, seed=0).build()
    rets = []
    for _ in range(16):
        algo.train()
        if algo._ep_returns:
            rets.append(np.mean(algo._ep_returns[-20:]))
        if rets and rets[-1] > -400:
            break
    # random play sits near -1300; -500 demonstrates a working policy
    assert rets[-1] > -500, f"TD3 final return {rets[-1]}"


def test_ddpg_step_runs():
    algo = DDPGConfig(env="Pendulum-v1", num_envs_per_worker=2,
                      rollout_length=32, learning_starts=64,
                      batch_size=32, seed=0).build()
    r = algo.train()
    assert r["steps_this_iter"] == 64
    ck = algo.save_checkpoint()
    algo.load_checkpoint(ck)
    a = algo.compute_action(np.zeros(3, np.float32))
    assert a.shape == (1,) and -2.0 <= float(a[0]) <= 2.0


def test_apex_dqn_inline_smoke():
    from ray_tpu.rllib.apex import ApexDQNConfig
    algo = ApexDQNConfig(env="CartPole-v1", num_rollout_workers=0,
                         num_envs_per_worker=2,
                         collect_steps_per_round=32,
                         train_rounds_per_iter=2,
                         grad_steps_per_round=2,
                         learning_starts=32, batch_size=16,
                         seed=0).build()
    r = algo.train()
    assert r["steps_this_iter"] > 0
    assert r["replay_size"] > 0
    algo.cleanup()


def test_apex_dqn_distributed(rt_init):
    from ray_tpu.rllib.apex import ApexDQNConfig
    algo = ApexDQNConfig(env="CartPole-v1", num_rollout_workers=2,
                         num_replay_shards=1,
                         num_envs_per_worker=2,
                         collect_steps_per_round=32,
                         train_rounds_per_iter=2,
                         grad_steps_per_round=2,
                         learning_starts=32, batch_size=16,
                         seed=0).build()
    assert algo._distributed
    r = algo.train()
    assert r["steps_this_iter"] > 0 and r["replay_size"] > 0
    algo.cleanup()


class TestConnectors:
    def test_mean_std_filter(self):
        f = MeanStdFilter()
        rng = np.random.default_rng(0)
        out = None
        for _ in range(200):
            out = f(rng.normal(5.0, 2.0, size=4))
        assert np.all(np.abs(out) < 5)
        # state round-trips
        cfg = f.to_config()
        from ray_tpu.rllib.connectors import Connector
        g = Connector.from_config(cfg)
        np.testing.assert_allclose(g._mean, f._mean)

    def test_frame_stack_resets(self):
        fs = FrameStack(k=3)
        a = fs(np.ones(2))
        assert a.shape == (3, 2)
        assert np.all(a[0] == 0) and np.all(a[2] == 1)
        fs.reset()
        b = fs(np.full(2, 7.0))
        assert np.all(b[0] == 0) and np.all(b[2] == 7)

    def test_action_connectors(self):
        clip = ClipActions([-1.0], [1.0])
        assert clip(np.array([3.0]))[0] == 1.0
        un = UnsquashActions([0.0], [10.0])
        np.testing.assert_allclose(un(np.array([0.0])), [5.0])
        rc = ClipReward(limit=1.0)
        assert rc(5.0) == 1.0 and rc(-3.0) == -1.0

    def test_pipeline_serialization(self):
        p = ConnectorPipeline([MeanStdFilter(), FrameStack(k=2)])
        p(np.zeros(3))
        q = ConnectorPipeline.from_config(p.to_config())
        assert len(q.connectors) == 2
        assert isinstance(q.connectors[0], MeanStdFilter)
        p.remove("FrameStack")
        assert len(p.connectors) == 1


def test_policy_server_roundtrip():
    server = PolicyServerInput(policy_fn=lambda obs: 1)
    try:
        client = PolicyClient(server.address)
        eid = client.start_episode()
        for t in range(5):
            a = client.get_action(eid, np.arange(4, dtype=np.float32))
            assert int(a) == 1
            client.log_returns(eid, 1.0)
        client.end_episode(eid)
        batch = server.next_batch(min_steps=5, timeout=5)
        assert batch is not None and batch.count == 5
        assert float(batch["rewards"].sum()) == 5.0
        assert server.episode_returns() == [5.0]
    finally:
        server.stop()


def test_ars_obs_filter_accumulates():
    algo = ARSConfig(env="CartPole-v1", pop_size=4, top_directions=2,
                     max_episode_steps=50, seed=0).build()
    assert algo.config.observation_filter == "MeanStdFilter"
    algo.train()
    assert algo._obs_n > 0                      # moments collected
    mean, std = algo._obs_stats()
    assert mean.shape == (4,) and (std > 0).all()
    ck = algo.save_checkpoint()
    assert ck["obs_n"] == algo._obs_n           # filter rides checkpoints
