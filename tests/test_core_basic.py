"""Core task API tests (reference analogue: python/ray/tests/test_basic.py)."""

import time

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def rt():
    ray_tpu.init(num_cpus=2, num_tpus=0)
    yield ray_tpu
    ray_tpu.shutdown()


@ray_tpu.remote
def add(a, b):
    return a + b


def test_simple_task(rt):
    assert rt.get(add.remote(1, 2), timeout=60) == 3


def test_task_with_kwargs(rt):
    @ray_tpu.remote
    def f(a, b=10):
        return a * b

    assert rt.get(f.remote(2), timeout=60) == 20
    assert rt.get(f.remote(2, b=3), timeout=60) == 6


def test_chained_tasks(rt):
    r1 = add.remote(1, 1)
    r2 = add.remote(r1, 1)
    r3 = add.remote(r2, r1)
    assert rt.get(r3, timeout=60) == 5


def test_nested_tasks(rt):
    @ray_tpu.remote
    def outer(x):
        return rt.get(add.remote(x, 1)) * 2

    assert rt.get(outer.remote(5), timeout=60) == 12


def test_nested_object_ref_in_structure(rt):
    ref = rt.put(41)

    @ray_tpu.remote
    def deref(d):
        # nested refs are NOT auto-resolved (same as the reference)
        return rt.get(d["ref"]) + 1

    assert rt.get(deref.remote({"ref": ref}), timeout=60) == 42


def test_task_error_propagates(rt):
    @ray_tpu.remote
    def boom():
        raise ValueError("kaput")

    with pytest.raises(ray_tpu.TaskError, match="kaput"):
        rt.get(boom.remote(), timeout=60)


def test_error_through_dependency(rt):
    @ray_tpu.remote
    def boom():
        raise ValueError("kaput")

    ref = add.remote(boom.remote(), 1)
    with pytest.raises(ray_tpu.TaskError, match="kaput"):
        rt.get(ref, timeout=60)


def test_num_returns(rt):
    @ray_tpu.remote(num_returns=3)
    def three():
        return 1, 2, 3

    a, b, c = three.remote()
    assert rt.get([a, b, c], timeout=60) == [1, 2, 3]


def test_dynamic_returns(rt):
    @ray_tpu.remote(num_returns="dynamic")
    def gen(n):
        for i in range(n):
            yield i * i

    g = rt.get(gen.remote(5), timeout=60)
    assert len(g) == 5
    assert [rt.get(r) for r in g] == [0, 1, 4, 9, 16]


def test_options_override(rt):
    f = add.options(name="my_add")
    assert rt.get(f.remote(2, 3), timeout=60) == 5


def test_wait(rt):
    @ray_tpu.remote
    def slow(t):
        time.sleep(t)
        return t

    fast_ref = slow.remote(0.01)
    slow_ref = slow.remote(5.0)
    ready, not_ready = rt.wait([fast_ref, slow_ref], num_returns=1,
                               timeout=30)
    assert ready == [fast_ref]
    assert not_ready == [slow_ref]


def test_wait_timeout(rt):
    @ray_tpu.remote
    def slow():
        time.sleep(30)

    ref = slow.remote()
    ready, not_ready = rt.wait([ref], num_returns=1, timeout=0.2)
    assert ready == []
    assert not_ready == [ref]


def test_large_arg_roundtrip(rt):
    arr = np.random.rand(500_000).astype(np.float32)  # ~2MB > inline limit

    @ray_tpu.remote
    def mean(x):
        return float(np.mean(x))

    assert abs(rt.get(mean.remote(arr), timeout=60) - arr.mean()) < 1e-5


def test_call_remote_function_directly_raises(rt):
    with pytest.raises(TypeError, match="remote"):
        add(1, 2)


def test_get_type_validation(rt):
    with pytest.raises(TypeError):
        rt.get(42)


def test_many_small_tasks(rt):
    refs = [add.remote(i, i) for i in range(100)]
    assert rt.get(refs, timeout=120) == [2 * i for i in range(100)]


def test_cluster_resources(rt):
    total = rt.cluster_resources()
    assert total["CPU"] == 2.0


def test_perf_harness_smoke():
    """The microbenchmark harness runs end-to-end and yields sane rates
    (reference: ray_perf.py smoke coverage).  Runs in a subprocess: the
    harness owns (and shuts down) its runtime, which must not collide
    with this module's shared fixture."""
    import json
    import os
    import subprocess
    import sys
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.perf", "--quick"],
        capture_output=True, text=True, timeout=240, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    results = {}
    for line in proc.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            r = json.loads(line)
            results[r["name"]] = r["value"]
    assert results["tasks_sync"] > 10, results
    assert results["actor_calls_sync"] > 10, results
    assert results["put_get_1mb"] > 5, results
    assert results["put_get_100mb"] > 0.05, results
