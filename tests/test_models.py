"""Model zoo tests (test-strategy analogue of the reference's model
coverage, e.g. rllib/models tests — here the zoo is framework-owned)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import gpt, mlp
from ray_tpu.parallel.mesh import create_mesh


@pytest.fixture(scope="module")
def tiny_cfg():
    return gpt.GPTConfig.tiny()


@pytest.fixture(scope="module")
def tiny_params(tiny_cfg):
    return gpt.init_params(tiny_cfg, jax.random.PRNGKey(0))


def test_gpt_forward_shapes(tiny_cfg, tiny_params):
    toks = jnp.zeros((2, 16), jnp.int32)
    logits = gpt.forward(tiny_params, toks, tiny_cfg)
    assert logits.shape == (2, 16, tiny_cfg.vocab_size)
    assert logits.dtype == jnp.float32


def test_gpt_loss_decreases(tiny_cfg, tiny_params):
    import optax
    from ray_tpu.train.step import make_train_step
    toks = jax.random.randint(jax.random.PRNGKey(1), (4, 33), 0,
                              tiny_cfg.vocab_size)
    batch = {"tokens": toks}
    init_fn, step_fn = make_train_step(
        lambda p, b: gpt.loss_fn(p, b, tiny_cfg), optax.adam(1e-2))
    state = init_fn(tiny_params)
    state, m0 = step_fn(state, batch)
    for _ in range(10):
        state, m = step_fn(state, batch)
    assert float(m["loss"]) < float(m0["loss"])


def test_gpt_sp_matches_reference(tiny_cfg, tiny_params):
    """Ring attention over an sp-sharded mesh == single-device attention."""
    mesh = create_mesh({"dp": 2, "sp": 4}, devices=jax.devices("cpu"))
    toks = jax.random.randint(jax.random.PRNGKey(2), (2, 65), 0,
                              tiny_cfg.vocab_size)
    l_sp = jax.jit(lambda p, b: gpt.loss_fn(p, b, tiny_cfg, mesh=mesh))(
        tiny_params, {"tokens": toks})
    l_ref = jax.jit(lambda p, b: gpt.loss_fn(p, b, tiny_cfg))(
        tiny_params, {"tokens": toks})
    np.testing.assert_allclose(float(l_sp), float(l_ref), rtol=1e-4)


def test_gpt_tp_matches_reference(tiny_cfg, tiny_params):
    mesh = create_mesh({"dp": 2, "tp": 4}, devices=jax.devices("cpu"))
    toks = jax.random.randint(jax.random.PRNGKey(3), (2, 33), 0,
                              tiny_cfg.vocab_size)
    l_tp = jax.jit(lambda p, b: gpt.loss_fn(p, b, tiny_cfg, mesh=mesh))(
        tiny_params, {"tokens": toks})
    l_ref = jax.jit(lambda p, b: gpt.loss_fn(p, b, tiny_cfg))(
        tiny_params, {"tokens": toks})
    np.testing.assert_allclose(float(l_tp), float(l_ref), rtol=1e-4)


def test_gpt_generate(tiny_cfg, tiny_params):
    prompt = jnp.array([[1, 2, 3]], jnp.int32)
    out = gpt.generate(tiny_params, tiny_cfg, prompt, max_new=5,
                       temperature=0.0)
    assert out.shape == (1, 8)
    assert (np.asarray(out[:, :3]) == np.asarray(prompt)).all()


def test_mlp_trains():
    cfg = mlp.MLPConfig(in_dim=8, hidden=(16,), out_dim=3)
    params = mlp.init_params(cfg, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (32, 8))
    y = (x[:, 0] > 0).astype(jnp.int32)
    batch = {"x": x, "y": y}
    loss0 = float(mlp.loss_fn(params, batch, cfg))
    grad = jax.grad(lambda p: mlp.loss_fn(p, batch, cfg))(params)
    params = jax.tree.map(lambda p, g: p - 0.5 * g, params, grad)
    assert float(mlp.loss_fn(params, batch, cfg)) < loss0


# -- resnet ----------------------------------------------------------------

def test_resnet_forward_and_train():
    from ray_tpu.models import resnet
    cfg = resnet.ResNetConfig.tiny(num_classes=4)
    params, state = resnet.init_params(cfg, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 16, 16, 3))
    y = jnp.array([0, 1, 2, 3])
    logits, new_state = resnet.forward(params, state, x, cfg, train=True)
    assert logits.shape == (4, 4)
    # BN running stats moved
    assert not np.allclose(np.asarray(new_state["stem_bn"]["mean"]),
                           np.asarray(state["stem_bn"]["mean"]))

    def step(p, s):
        (l, (s2, m)), g = jax.value_and_grad(
            lambda p: resnet.loss_fn(p, s, {"x": x, "y": y}, cfg),
            has_aux=True)(p)
        p = jax.tree.map(lambda a, b: a - 0.1 * b, p, g)
        return p, s2, l
    l0 = None
    for _ in range(8):
        params, state, l = step(params, state)
        l0 = l if l0 is None else l0
    assert float(l) < float(l0)


def test_resnet_eval_deterministic():
    from ray_tpu.models import resnet
    cfg = resnet.ResNetConfig.tiny()
    params, state = resnet.init_params(cfg, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 8, 3))
    l1, st = resnet.forward(params, state, x, cfg, train=False)
    l2, _ = resnet.forward(params, state, x, cfg, train=False)
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l2))
    # eval does not update stats
    np.testing.assert_allclose(np.asarray(st["stem_bn"]["mean"]),
                               np.asarray(state["stem_bn"]["mean"]))


def test_resnet50_shapes():
    from ray_tpu.models import resnet
    cfg = resnet.ResNetConfig.resnet50(num_classes=10, cifar_stem=False,
                                       dtype=jnp.float32, num_filters=8)
    params, state = resnet.init_params(cfg, jax.random.PRNGKey(0))
    x = jnp.zeros((1, 64, 64, 3))
    logits, _ = resnet.forward(params, state, x, cfg, train=False)
    assert logits.shape == (1, 10)


# -- bert ------------------------------------------------------------------

def test_bert_mlm_loss_and_mask():
    from ray_tpu.models import bert
    cfg = bert.BERTConfig.tiny()
    params = bert.init_params(cfg, jax.random.PRNGKey(0))
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0,
                             cfg.vocab_size)
    labels = jnp.full((2, 32), cfg.ignore_index).at[:, 3].set(ids[:, 3])
    loss = bert.loss_fn(params, {"input_ids": ids, "labels": labels}, cfg)
    assert np.isfinite(float(loss))
    # attention_mask: padding must not change unmasked-position loss much
    am = jnp.ones((2, 32), jnp.int32)
    l2 = bert.loss_fn(params, {"input_ids": ids, "labels": labels,
                               "attention_mask": am}, cfg)
    np.testing.assert_allclose(float(loss), float(l2), rtol=1e-5)


def test_bert_trains():
    import optax
    from ray_tpu.models import bert
    from ray_tpu.train.step import make_train_step
    cfg = bert.BERTConfig.tiny(n_layers=1)
    params = bert.init_params(cfg, jax.random.PRNGKey(0))
    ids = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0,
                             cfg.vocab_size)
    labels = ids  # predict every token (degenerate MLM)
    batch = {"input_ids": ids, "labels": labels}
    init_fn, step_fn = make_train_step(
        lambda p, b: bert.loss_fn(p, b, cfg), optax.adam(1e-2))
    s = init_fn(params)
    s, m0 = step_fn(s, batch)
    for _ in range(10):
        s, m = step_fn(s, batch)
    assert float(m["loss"]) < float(m0["loss"])


def test_bert_tp_matches_reference():
    from ray_tpu.models import bert
    cfg = bert.BERTConfig.tiny()
    params = bert.init_params(cfg, jax.random.PRNGKey(0))
    mesh = create_mesh({"dp": 2, "tp": 4}, devices=jax.devices("cpu"))
    ids = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0,
                             cfg.vocab_size)
    batch = {"input_ids": ids, "labels": ids}
    l_tp = jax.jit(lambda p, b: bert.loss_fn(p, b, cfg, mesh=mesh))(
        params, batch)
    l_ref = jax.jit(lambda p, b: bert.loss_fn(p, b, cfg))(params, batch)
    np.testing.assert_allclose(float(l_tp), float(l_ref), rtol=1e-4)


# -- rl model zoo ----------------------------------------------------------

def test_actor_critic_fcnet():
    from ray_tpu.models.zoo import ActorCritic, ModelConfig
    net = ActorCritic(ModelConfig(kind="fcnet", obs_shape=(4,),
                                  num_actions=2, fcnet_hiddens=(32,)))
    params = net.init(jax.random.PRNGKey(0))
    logits, value = net.apply(params, jnp.zeros((3, 4)))
    assert logits.shape == (3, 2) and value.shape == (3,)


def test_actor_critic_visionnet():
    from ray_tpu.models.zoo import ActorCritic, ModelConfig
    net = ActorCritic(ModelConfig(kind="visionnet", obs_shape=(84, 84, 4),
                                  num_actions=6))
    params = net.init(jax.random.PRNGKey(0))
    obs = jnp.zeros((2, 84, 84, 4), jnp.uint8)
    logits, value = net.apply(params, obs)
    assert logits.shape == (2, 6) and value.shape == (2,)


def test_actor_critic_lstm():
    from ray_tpu.models.zoo import ActorCritic, ModelConfig
    net = ActorCritic(ModelConfig(kind="lstm", obs_shape=(4,),
                                  num_actions=2, cell_size=16))
    assert net.is_recurrent
    params = net.init(jax.random.PRNGKey(0))
    obs = jax.random.normal(jax.random.PRNGKey(1), (2, 5, 4))
    logits, value, state = net.apply_seq(params, obs)
    assert logits.shape == (2, 5, 2) and value.shape == (2, 5)
    assert state[0].shape == (2, 16)
    # carry state across windows
    logits2, _, state2 = net.apply_seq(params, obs, state)
    assert not np.allclose(np.asarray(logits), np.asarray(logits2))


def test_actor_critic_gtrxl_causal():
    from ray_tpu.models.zoo import ActorCritic, ModelConfig
    net = ActorCritic(ModelConfig(kind="gtrxl", obs_shape=(4,),
                                  num_actions=3, attn_dim=16,
                                  attn_layers=1))
    params = net.init(jax.random.PRNGKey(0))
    obs = jax.random.normal(jax.random.PRNGKey(1), (1, 6, 4))
    logits, _, _ = net.apply_seq(params, obs)
    # causality: perturbing the future must not change the past
    obs2 = obs.at[:, 4:].add(1.0)
    logits2, _, _ = net.apply_seq(params, obs2)
    np.testing.assert_allclose(np.asarray(logits[:, :4]),
                               np.asarray(logits2[:, :4]), atol=1e-5)
