"""Model zoo tests (test-strategy analogue of the reference's model
coverage, e.g. rllib/models tests — here the zoo is framework-owned)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import gpt, mlp
from ray_tpu.parallel.mesh import create_mesh


@pytest.fixture(scope="module")
def tiny_cfg():
    return gpt.GPTConfig.tiny()


@pytest.fixture(scope="module")
def tiny_params(tiny_cfg):
    return gpt.init_params(tiny_cfg, jax.random.PRNGKey(0))


def test_gpt_forward_shapes(tiny_cfg, tiny_params):
    toks = jnp.zeros((2, 16), jnp.int32)
    logits = gpt.forward(tiny_params, toks, tiny_cfg)
    assert logits.shape == (2, 16, tiny_cfg.vocab_size)
    assert logits.dtype == jnp.float32


def test_gpt_loss_decreases(tiny_cfg, tiny_params):
    import optax
    from ray_tpu.train.step import make_train_step
    toks = jax.random.randint(jax.random.PRNGKey(1), (4, 33), 0,
                              tiny_cfg.vocab_size)
    batch = {"tokens": toks}
    init_fn, step_fn = make_train_step(
        lambda p, b: gpt.loss_fn(p, b, tiny_cfg), optax.adam(1e-2))
    state = init_fn(tiny_params)
    state, m0 = step_fn(state, batch)
    for _ in range(10):
        state, m = step_fn(state, batch)
    assert float(m["loss"]) < float(m0["loss"])


def test_gpt_sp_matches_reference(tiny_cfg, tiny_params):
    """Ring attention over an sp-sharded mesh == single-device attention."""
    mesh = create_mesh({"dp": 2, "sp": 4}, devices=jax.devices("cpu"))
    toks = jax.random.randint(jax.random.PRNGKey(2), (2, 65), 0,
                              tiny_cfg.vocab_size)
    l_sp = jax.jit(lambda p, b: gpt.loss_fn(p, b, tiny_cfg, mesh=mesh))(
        tiny_params, {"tokens": toks})
    l_ref = jax.jit(lambda p, b: gpt.loss_fn(p, b, tiny_cfg))(
        tiny_params, {"tokens": toks})
    np.testing.assert_allclose(float(l_sp), float(l_ref), rtol=1e-4)


def test_gpt_tp_matches_reference(tiny_cfg, tiny_params):
    mesh = create_mesh({"dp": 2, "tp": 4}, devices=jax.devices("cpu"))
    toks = jax.random.randint(jax.random.PRNGKey(3), (2, 33), 0,
                              tiny_cfg.vocab_size)
    l_tp = jax.jit(lambda p, b: gpt.loss_fn(p, b, tiny_cfg, mesh=mesh))(
        tiny_params, {"tokens": toks})
    l_ref = jax.jit(lambda p, b: gpt.loss_fn(p, b, tiny_cfg))(
        tiny_params, {"tokens": toks})
    np.testing.assert_allclose(float(l_tp), float(l_ref), rtol=1e-4)


def test_gpt_generate(tiny_cfg, tiny_params):
    prompt = jnp.array([[1, 2, 3]], jnp.int32)
    out = gpt.generate(tiny_params, tiny_cfg, prompt, max_new=5,
                       temperature=0.0)
    assert out.shape == (1, 8)
    assert (np.asarray(out[:, :3]) == np.asarray(prompt)).all()


def test_mlp_trains():
    cfg = mlp.MLPConfig(in_dim=8, hidden=(16,), out_dim=3)
    params = mlp.init_params(cfg, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (32, 8))
    y = (x[:, 0] > 0).astype(jnp.int32)
    batch = {"x": x, "y": y}
    loss0 = float(mlp.loss_fn(params, batch, cfg))
    grad = jax.grad(lambda p: mlp.loss_fn(p, batch, cfg))(params)
    params = jax.tree.map(lambda p, g: p - 0.5 * g, params, grad)
    assert float(mlp.loss_fn(params, batch, cfg)) < loss0
