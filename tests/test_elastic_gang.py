"""Elastic gang: shrink-and-resume without restarting survivors.

Covers the gang layer (reform / readmit / prompt member-death
surfacing / formation-leak cleanup) in tier-1, and the trainer-level
kill-a-host-mid-epoch + head-loss-mid-fit flows behind ``slow``.
"""

from __future__ import annotations

import os
import signal
import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.parallel.gang import GangMember, GangMemberDied, MultiHostGang


@pytest.fixture
def rt():
    ray_tpu.init(num_cpus=6, num_tpus=0)
    yield ray_tpu
    ray_tpu.shutdown()


def _spmd_sum(rank):
    """Cross-process allreduce whose value encodes the WORLD SIZE, so a
    reformed gang provably reshards dp to the new world."""
    import jax
    import jax.numpy as jnp
    import numpy as _np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    devs = jax.devices()
    mesh = Mesh(_np.array(devs).reshape(len(devs)), ("dp",))
    sh = NamedSharding(mesh, P("dp"))
    local = _np.full((1, 4), float(rank + 1))
    garr = jax.make_array_from_process_local_data(
        sh, local, (jax.process_count() or 1, 4))
    return float(jax.jit(jnp.sum)(garr))


class FailingSetupMember(GangMember):
    """Rank 1's setup dies — the partial-formation shape."""

    def setup(self, coordinator: str) -> dict:
        if self.rank == 1:
            raise RuntimeError("injected setup failure (rank 1)")
        return super().setup(coordinator)


def _gang_actor_states(client) -> list[str]:
    reply = client.request({"t": "state", "what": "actors"}, timeout=30)
    return [a["state"] for a in reply["data"]
            if "Member" in a.get("class_name", "")]


def test_partial_formation_kills_all_members(rt):
    """One member's setup failing must not leak the other member
    actors (they used to stay alive — and hold their reservations —
    forever)."""
    with pytest.raises(Exception, match="injected setup failure"):
        MultiHostGang(2, cpu_backend=True, devices_per_member=1,
                      member_cls=FailingSetupMember, setup_timeout=120)
    client = ray_tpu.get_runtime().client
    deadline = time.time() + 60
    while time.time() < deadline:
        states = _gang_actor_states(client)
        if states and all(s == "dead" for s in states):
            return
        time.sleep(0.2)
    pytest.fail(f"leaked gang members after failed formation: "
                f"{_gang_actor_states(client)}")


def test_member_death_during_run_names_rank_promptly(rt):
    gang = MultiHostGang(2, cpu_backend=True, devices_per_member=1)
    try:
        pids = gang.member_pids()

        def long_attempt(rank):
            time.sleep(120)
            return rank

        holder: dict = {}

        def run():
            t0 = time.perf_counter()
            try:
                gang.run(long_attempt)
            except Exception as e:
                holder["error"] = e
            holder["elapsed"] = time.perf_counter() - t0

        t = threading.Thread(target=run)
        t.start()
        time.sleep(2.0)          # let the run land on both members
        os.kill(pids[1], signal.SIGKILL)
        t.join(timeout=60)
        assert not t.is_alive(), "run() hung after member death"
        err = holder.get("error")
        assert isinstance(err, GangMemberDied), err
        assert err.rank == 1                      # names the dead rank
        assert "rank 1" in str(err)
        assert holder["elapsed"] < 30, \
            f"death took {holder['elapsed']:.1f}s to surface"
    finally:
        gang.shutdown()


@pytest.mark.slow
@pytest.mark.chaos
def test_reform_shrinks_then_readmits_without_restarting_survivors(rt):
    """THE elastic contract: kill one of three members; reform keeps
    the survivors' PROCESSES (same pids) and reshards dp to world 2;
    readmit grows back to 3 with one fresh process, survivors still
    untouched."""
    gang = MultiHostGang(3, cpu_backend=True, devices_per_member=1)
    try:
        pids = gang.member_pids()
        assert len(set(pids)) == 3
        assert gang.run(_spmd_sum, timeout=300) == [24.0] * 3  # (1+2+3)*4

        os.kill(pids[1], signal.SIGKILL)
        alive = []
        deadline = time.time() + 60
        while time.time() < deadline:
            alive = gang.alive_ranks()
            if alive == [0, 2]:
                break
            time.sleep(0.2)
        assert alive == [0, 2], alive

        gang.reform(alive)
        assert gang.num_members == 2
        new_pids = gang.member_pids()
        assert new_pids == [pids[0], pids[2]]     # survivors NOT restarted
        # dp resharded to the new world: ranks are 0,1 now → (1+2)*4
        assert gang.run(_spmd_sum, timeout=300) == [12.0] * 2

        assert gang.readmit() == 3                # back to target world
        final_pids = gang.member_pids()
        assert final_pids[:2] == [pids[0], pids[2]]
        assert final_pids[2] not in pids          # a fresh replacement
        assert gang.run(_spmd_sum, timeout=300) == [24.0] * 3
    finally:
        gang.shutdown()


# ---------------------------------------------------------------------------
# trainer-level flows (long: behind slow)


def _make_trainer(tmp_path, num_hosts, num_steps=30, name="elastic"):
    import jax.numpy as jnp
    import optax

    from ray_tpu.train import JaxTrainer
    from ray_tpu.train.config import (FailureConfig, RunConfig,
                                      ScalingConfig)

    class SlowBatches:
        def __init__(self, n):
            self.n = n

        def __iter__(self):
            rng = np.random.RandomState(0)
            for _ in range(self.n):
                time.sleep(0.12)
                yield {"x": rng.rand(6, 4).astype(np.float32)}

    def loss_fn(params, batch):
        pred = batch["x"] @ params["w"]
        return jnp.mean((pred - 1.0) ** 2)

    def init_params(key):
        import jax
        return {"w": jax.random.normal(key, (4, 1)) * 0.1}

    return JaxTrainer(
        loss_fn=loss_fn, init_params=init_params,
        optimizer=optax.adam(0.1),
        train_data=SlowBatches(num_steps + 5),
        num_steps=num_steps,
        params_logical=None, rules=(),
        report_every=5, checkpoint_every=5,
        scaling_config=ScalingConfig(mesh={"dp": -1}, num_hosts=num_hosts,
                                     use_cpu_devices=True,
                                     devices_per_host=1),
        run_config=RunConfig(name=name, storage_path=str(tmp_path),
                             failure_config=FailureConfig(max_failures=2)))


def _wait_for_checkpoint(tmp_path, name, timeout=120):
    root = os.path.join(str(tmp_path), name, "checkpoints")
    deadline = time.time() + timeout
    while time.time() < deadline:
        if os.path.isdir(root) and any(
                d.startswith("checkpoint_") for d in os.listdir(root)):
            return
        time.sleep(0.1)
    pytest.fail("no checkpoint appeared before the kill")


@pytest.mark.slow
@pytest.mark.chaos
def test_trainer_kill_host_mid_epoch_shrinks_and_resumes(rt, tmp_path):
    """Acceptance: kill one of three members mid-epoch; the gang
    shrinks 3→2, the SURVIVING member processes keep their pids,
    training resumes from the last checkpoint and reaches the target
    step — no full-gang restart."""
    num_steps = 30
    trainer = _make_trainer(tmp_path, num_hosts=3, num_steps=num_steps)
    gang = trainer.gang
    pids = gang.member_pids()
    assert len(set(pids)) == 3

    holder: dict = {}

    def run_fit():
        try:
            holder["result"] = trainer.fit()
        except Exception as e:
            holder["error"] = e

    t = threading.Thread(target=run_fit)
    t.start()
    _wait_for_checkpoint(tmp_path, "elastic")
    os.kill(pids[1], signal.SIGKILL)

    t.join(timeout=600)
    assert not t.is_alive(), "fit() hung after member death"
    assert "error" not in holder, holder.get("error")
    result = holder["result"]
    assert result.error is None
    assert result.metrics["step"] == num_steps
    steps_seen = [m["step"] for m in result.metrics_history]
    assert steps_seen[-1] == num_steps

    # the elastic contract, post-hoc: same gang object, shrunk to the
    # survivors, whose processes were never restarted
    gang2 = trainer.gang
    assert gang2 is gang
    assert gang2.num_members == 2
    assert gang2.member_pids() == [pids[0], pids[2]]


@pytest.mark.slow
@pytest.mark.chaos
def test_trainer_head_killed_mid_fit_completes_via_promotion(tmp_path):
    """Acceptance: the head MACHINE dies mid-fit (local snapshot gone);
    a replacement head is promoted from a surviving node's replica;
    training completes with no client-visible error."""
    from ray_tpu.cluster_utils import Cluster

    c = Cluster(head_persistence=True)
    try:
        n0 = c.add_node(num_cpus=4)
        c.add_node(num_cpus=4)
        c.wait_for_nodes()
        ray_tpu.init(address=n0.address)

        num_steps = 30
        trainer = _make_trainer(tmp_path, num_hosts=2, num_steps=num_steps,
                                name="headloss")
        holder: dict = {}

        def run_fit():
            try:
                holder["result"] = trainer.fit()
            except Exception as e:
                holder["error"] = e

        t = threading.Thread(target=run_fit)
        t.start()
        _wait_for_checkpoint(tmp_path, "headloss")

        # kill the head mid-epoch, snapshot included (machine loss)...
        c.head.stop()
        time.sleep(2.0)
        # ...and promote a replacement from the freshest node replica
        c.restart_head(simulate_machine_loss=True)

        t.join(timeout=600)
        assert not t.is_alive(), "fit() hung across head failover"
        assert "error" not in holder, holder.get("error")
        result = holder["result"]
        assert result.error is None
        assert result.metrics["step"] == num_steps
    finally:
        try:
            ray_tpu.shutdown()
        except Exception:
            pass
        c.shutdown()
