"""Elastic streamed ingest (train/ingest.py): pure-function sharding,
the exactly-once sample ledger, spool/manifest positional reads, the
per-step data_dispatch chaos point, and the driver-side gang_readmit
chaos point.  The full kill-shrink-regrow trainer flow lives in
test_data_chaos_e2e.py behind ``slow``.
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu.core import fault_injection as fi
from ray_tpu.data import Dataset
from ray_tpu.train.ingest import (DatasetShard, SampleLedger, ensure_spooled,
                                  merge_ledgers, shard_range, spool_epoch,
                                  validate_ledger)


@pytest.fixture(autouse=True)
def _no_leftover_plan():
    yield
    fi.uninstall()


def _spool(tmp_path, n=128):
    ds = Dataset.range(n).map_batches(
        lambda b: {"x": b["id"], "y": b["id"] * 2.0})
    return ensure_spooled(ds, str(tmp_path / "spool"))


# ---------------------------------------------------------------------------
# pure-function sharding


def test_shard_range_tiles_every_world_size():
    """THE re-sharding rule: for any world size the per-rank slices
    tile the step's global range exactly — so a resize needs no data
    movement and no negotiation, just the new (rank, world)."""
    for world in (1, 2, 3, 4, 5, 7, 8):
        for step in (0, 1, 9):
            got = sorted(x for r in range(world)
                         for x in range(*shard_range(step, 16, r, world)))
            assert got == list(range(step * 16, (step + 1) * 16)), \
                (world, step)


def test_shard_range_is_contiguous_and_near_even():
    sizes = [b - a for (a, b) in
             (shard_range(0, 19, r, 4) for r in range(4))]
    assert sorted(sizes) == [4, 5, 5, 5]


# ---------------------------------------------------------------------------
# ledger audit rules


def _led(entries):
    led = SampleLedger()
    for e in entries:
        led.record(*e[:4], attempt=e[4], epoch=e[5] if len(e) > 5 else 0)
    return led


def test_validate_ledger_clean_run():
    led = _led([(r, s, *shard_range(s, 8, r, 2), 0, 0)
                for s in range(4) for r in range(2)])
    v = validate_ledger(led, 4, 8)
    assert v["ok"] and not v["missing"] and not v["double_fed"]


def test_validate_ledger_detects_gap_and_double_feed():
    led = _led([(0, 0, 0, 4, 0, 0)])            # rank 1's half missing
    v = validate_ledger(led, 1, 8)
    assert not v["ok"] and v["missing"] == [[0, 4, 8]]

    led = _led([(0, 0, 0, 5, 0, 0), (1, 0, 3, 8, 0, 0)])  # [3,5) twice
    v = validate_ledger(led, 1, 8)
    assert not v["ok"] and v["double_fed"] == [[0, 3, 5]]


def test_validate_ledger_higher_attempt_supersedes():
    """Checkpoint-consistency: a step delivered by attempt 0 at world 2
    AND re-delivered by attempt 1 at world 3 counts ONCE — the highest
    attempt is the surviving delivery, the rolled-back one is not a
    double-feed."""
    led = _led([(r, 1, *shard_range(1, 12, r, 2), 0, 0) for r in range(2)]
               + [(r, 1, *shard_range(1, 12, r, 3), 1, 0)
                  for r in range(3)]
               + [(r, 0, *shard_range(0, 12, r, 2), 0, 0)
                  for r in range(2)])
    v = validate_ledger(led, 2, 12)
    assert v["ok"], v
    # and a PARTIAL higher attempt exposes the gap it left
    led.record(0, 0, *shard_range(0, 12, 0, 3), attempt=1)
    v = validate_ledger(led, 2, 12)
    assert not v["ok"] and v["missing"]


def test_ledger_wire_roundtrip_and_files(tmp_path):
    led = _led([(0, 0, 0, 8, 0, 0), (0, 1, 8, 16, 0, 0)])
    m = led.to_wire(epoch=0)
    assert m["t"] == "sample_ledger"
    assert SampleLedger.from_wire(m).to_wire() == m
    with pytest.raises(ValueError, match="sample_ledger"):
        SampleLedger.from_wire({"t": "prefix_publish"})
    p = str(tmp_path / "rank0-attempt0.json")
    led.save(p)
    assert SampleLedger.load(p).max_step() == 1
    # merged output must not feed back into future merges
    merged = merge_ledgers(str(tmp_path),
                           save_to=str(tmp_path / "merged.json"))
    assert len(merged) == 2
    assert len(merge_ledgers(str(tmp_path))) == 2


# ---------------------------------------------------------------------------
# spool + positional shard reads


def test_spool_and_shard_exactly_once_across_resize(tmp_path):
    """The tentpole invariant, unit-sized: world 2 delivers steps 0..2,
    a 'shrink' resumes at step 3 with world 1 and a higher attempt —
    the merged ledger proves every sample of the epoch delivered
    exactly once, and the batches re-shard with no data movement."""
    man = _spool(tmp_path, n=128)
    assert man.total_rows == 128 and man.row_offsets[-1] == 128
    ld = str(tmp_path / "ledger")

    seen = []
    for r in range(2):
        sh = DatasetShard(man.path, rank=r, world=2, global_batch=16,
                          ledger_dir=ld, attempt=0)
        assert sh.steps_per_epoch == 8
        for step, batch in sh.iter_batches():
            if step >= 3:
                break
            seen.extend(np.asarray(batch["x"]).tolist())
            assert np.array_equal(batch["y"], batch["x"] * 2.0)

    sh = DatasetShard(man.path, rank=0, world=1, global_batch=16,
                      ledger_dir=ld, attempt=1)
    for step, batch in sh.iter_batches(start_step=3):
        seen.extend(np.asarray(batch["x"]).tolist())
    assert sorted(seen) == list(range(128))

    merged = merge_ledgers(ld)
    v = validate_ledger(merged, 8, 16)
    # steps 0..2 at attempt 0 world 2; 3..7 at attempt 1 world 1 — but
    # attempt 0's break left step 3 recorded-and-rolled-back: the
    # supersede rule absorbs it
    assert v["ok"], v


def test_shard_reads_cross_block_boundaries(tmp_path):
    man = _spool(tmp_path, n=100)        # 8 blocks of 12/13 rows
    sh = DatasetShard(man.path, rank=0, world=1, global_batch=25,
                      ledger_dir=str(tmp_path / "led"))
    rows = sh.read_rows(10, 40)          # spans >= 2 blocks
    assert np.array_equal(rows["x"], np.arange(10, 40))


def test_spool_is_idempotent_and_manifest_pinned(tmp_path):
    man1 = _spool(tmp_path)
    man2 = _spool(tmp_path)              # must NOT respool
    assert man2.block_files == man1.block_files
    with open(man1.path) as f:
        m = json.load(f)
    assert m["t"] == "ingest_manifest"
    assert m["row_offsets"][0] == 0 and m["total_rows"] == 128


def test_multi_epoch_steps_and_epoch_local_ranges(tmp_path):
    man = _spool(tmp_path, n=64)
    ld = str(tmp_path / "led")
    sh = DatasetShard(man.path, rank=0, world=1, global_batch=32,
                      ledger_dir=ld, epochs=2)
    assert sh.total_steps == 4
    trail = [(step, int(batch["x"][0])) for step, batch
             in sh.iter_batches()]
    # global step keeps counting, epoch-local position wraps
    assert trail == [(0, 0), (1, 32), (2, 0), (3, 32)]
    eps = {e.step: e.epoch for e in sh.ledger.entries}
    assert eps == {0: 0, 1: 0, 2: 1, 3: 1}


# ---------------------------------------------------------------------------
# chaos points


def test_shard_data_dispatch_fires_per_step(tmp_path):
    man = _spool(tmp_path, n=64)
    plan = fi.FaultPlan()
    seen = []
    plan.script(lambda ctx: seen.append(dict(ctx)),
                point="data_dispatch", nth=None, times=1000)
    fi.install(plan)
    try:
        sh = DatasetShard(man.path, rank=1, world=2, global_batch=16,
                          ledger_dir=str(tmp_path / "led"))
        list(sh.iter_batches())
    finally:
        fi.uninstall()
    assert [c["step"] for c in seen] == list(range(4))
    assert all(c["shard"] == "train" and c["rank"] == 1 for c in seen)


def test_shard_scripted_failure_at_exact_step(tmp_path):
    """A raising rule kills the feed at the scripted step — the member
    dies BEFORE recording the step, so the ledger shows the rollback
    the e2e audit relies on."""
    man = _spool(tmp_path, n=64)
    ld = str(tmp_path / "led")

    def boom(ctx):
        raise RuntimeError(f"scripted ingest fault at step {ctx['step']}")

    plan = fi.FaultPlan()
    plan.script(boom, point="data_dispatch", nth=3, times=1)
    fi.install(plan)
    sh = DatasetShard(man.path, rank=0, world=1, global_batch=16,
                      ledger_dir=ld)
    with pytest.raises(RuntimeError, match="at step 2"):
        list(sh.iter_batches())
    fi.uninstall()
    assert sh.ledger.max_step() == 1     # step 2 never recorded


def test_gang_readmit_chaos_point_scripted_failure():
    """Driver-side gang_readmit: a scripted raise at the re-admission
    boundary exercises the readmission-failure path BEFORE any
    replacement actor spawns; disarmed, the same readmit succeeds."""
    ray_tpu.init(num_cpus=6, num_tpus=0)
    gang = None
    try:
        from ray_tpu.parallel.gang import MultiHostGang
        import signal
        import time
        gang = MultiHostGang(3, cpu_backend=True, devices_per_member=1)
        pids = gang.member_pids()
        os.kill(pids[1], signal.SIGKILL)
        deadline = time.time() + 60
        while time.time() < deadline:
            if gang.alive_ranks() == [0, 2]:
                break
            time.sleep(0.2)
        assert gang.alive_ranks() == [0, 2]
        gang.reform([0, 2])
        assert gang.num_members == 2 and gang.target_members == 3

        def boom(ctx):
            raise RuntimeError(
                f"scripted readmit fault (world={ctx['world']}, "
                f"want={ctx['want']})")

        plan = fi.FaultPlan()
        plan.script(boom, point="gang_readmit", nth=None, times=1)
        fi.install(plan)
        with pytest.raises(RuntimeError, match="scripted readmit fault"):
            gang.readmit()
        assert gang.num_members == 2     # no side effects before the gate
        assert any(p == "gang_readmit" for (p, _a, _d) in plan.log)
        fi.uninstall()
        assert gang.readmit() == 3       # disarmed: readmission works
    finally:
        if gang is not None:
            gang.shutdown()
        ray_tpu.shutdown()
