"""Multi-host gang tests: real jax.distributed across >=2 member
processes launched through the actor API, SPMD training over the global
mesh, and kill-one-member restart-from-checkpoint recovery.

Reference analogue: python/ray/train/tests/test_backend.py +
backend_executor.py:94 (start), :571 (restart), with jax.distributed
replacing the torch process-group rendezvous (train/torch/config.py:69).
Runs on the CPU backend (collectives ride Gloo), the multi-host test
shape for machines without multiple TPU hosts.
"""

from __future__ import annotations

import os
import signal
import threading
import time

import numpy as np
import pytest

import ray_tpu


@pytest.fixture
def rt():
    ray_tpu.init(num_cpus=6, num_tpus=0)
    yield ray_tpu
    ray_tpu.shutdown()


def test_gang_formation_and_spmd_collective(rt):
    from ray_tpu.parallel.gang import MultiHostGang

    gang = MultiHostGang(2, cpu_backend=True, devices_per_member=2)
    try:
        assert [i["rank"] for i in gang.infos] == [0, 1]
        assert all(i["global_devices"] == 4 for i in gang.infos)
        assert all(i["local_devices"] == 2 for i in gang.infos)
        assert len(set(i["pid"] for i in gang.infos)) == 2  # real processes

        def spmd_sum(rank):
            import jax
            import jax.numpy as jnp
            import numpy as _np
            from jax.sharding import (Mesh, NamedSharding,
                                      PartitionSpec as P)
            devs = jax.devices()
            mesh = Mesh(_np.array(devs).reshape(len(devs)), ("dp",))
            sh = NamedSharding(mesh, P("dp"))
            local = _np.full((2, 4), float(rank + 1))
            garr = jax.make_array_from_process_local_data(sh, local, (4, 4))
            # cross-process all-reduce: every rank must see the global sum
            return float(jax.jit(jnp.sum)(garr))

        out = gang.run(spmd_sum)
        assert out == [24.0, 24.0], out   # (1+2)*2rows*4cols
    finally:
        gang.shutdown()


def test_jax_trainer_multihost_kill_and_restore(rt, tmp_path):
    """The headline FT path: JaxTrainer SPMD over a 2-process gang;
    SIGKILL one member mid-run; the trainer re-forms a fresh gang and
    resumes from the last rank-0 checkpoint."""
    import jax.numpy as jnp
    import optax

    from ray_tpu.train import JaxTrainer
    from ray_tpu.train.config import (FailureConfig, RunConfig,
                                      ScalingConfig)

    class SlowBatches:
        """Deterministic, picklable, rate-limited batch stream (every
        member sees the same sequence; shard_batch carves per-process
        rows)."""

        def __init__(self, n):
            self.n = n

        def __iter__(self):
            rng = np.random.RandomState(0)
            for _ in range(self.n):
                time.sleep(0.12)
                yield {"x": rng.rand(8, 4).astype(np.float32)}

    def loss_fn(params, batch):
        pred = batch["x"] @ params["w"]
        return jnp.mean((pred - 1.0) ** 2)

    def init_params(key):
        import jax
        return {"w": jax.random.normal(key, (4, 1)) * 0.1}

    num_steps = 30
    trainer = JaxTrainer(
        loss_fn=loss_fn, init_params=init_params,
        optimizer=optax.adam(0.1),
        train_data=SlowBatches(num_steps + 5),
        num_steps=num_steps,
        params_logical=None, rules=(),
        report_every=5, checkpoint_every=5,
        scaling_config=ScalingConfig(mesh={"dp": -1}, num_hosts=2,
                                     use_cpu_devices=True,
                                     devices_per_host=2),
        run_config=RunConfig(name="mh", storage_path=str(tmp_path),
                             failure_config=FailureConfig(max_failures=2)))

    gang = trainer.gang   # pre-form so the test can see member pids
    pids = gang.member_pids()
    assert len(set(pids)) == 2

    holder: dict = {}

    def run_fit():
        try:
            holder["result"] = trainer.fit()
        except Exception as e:   # surfaced to the main thread below
            holder["error"] = e

    t = threading.Thread(target=run_fit)
    t.start()

    # wait for the first rank-0 checkpoint to land, then kill member 1
    ckpt_root = os.path.join(str(tmp_path), "mh", "checkpoints")
    deadline = time.time() + 90
    while time.time() < deadline:
        if os.path.isdir(ckpt_root) and any(
                d.startswith("checkpoint_") for d in os.listdir(ckpt_root)):
            break
        time.sleep(0.1)
    else:
        pytest.fail("no checkpoint appeared before the kill")
    os.kill(pids[1], signal.SIGKILL)

    t.join(timeout=300)
    assert not t.is_alive(), "fit() hung after member death"
    assert "error" not in holder, holder.get("error")
    result = holder["result"]
    assert result.error is None
    assert result.metrics["step"] == num_steps
    # training actually recovered: the restored run continued past the
    # kill point and the loss kept improving
    steps_seen = [m["step"] for m in result.metrics_history]
    assert steps_seen[-1] == num_steps
    assert result.metrics["loss"] < result.metrics_history[0]["loss"]
