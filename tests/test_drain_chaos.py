"""Chaos coverage for the drain protocol (ISSUE 14): planned removal
must stay graceful under real failures — a replica drain interrupted by
a genuine kill falls back to token-exact resume, a node killed
mid-decommission still converges via lineage reconstruction, and a
drain that can't finish takes the EXPLICIT timeout path (counted, never
masked).  All scripted through ``FaultPlan.on_drain``."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.cluster_utils import Cluster
from ray_tpu.core import fault_injection
from ray_tpu.inference import EngineConfig, build_gpt_deployment
from ray_tpu.models import gpt
from ray_tpu.serve import fleet
from ray_tpu.serve.fleet import FleetConfig

pytestmark = pytest.mark.chaos

CFG = gpt.GPTConfig.tiny(dtype=jnp.float32, max_seq=64)
SEED = 0


@pytest.fixture(autouse=True)
def _cleanup():
    yield
    fault_injection.uninstall()
    serve.shutdown()


def _ref_tokens(prompt, max_new):
    params = gpt.init_params(CFG, jax.random.PRNGKey(SEED))
    out = gpt.generate(params, CFG, jnp.asarray([prompt], jnp.int32),
                       max_new=max_new, temperature=0.0)
    return np.asarray(out)[0, len(prompt):].tolist()


def _run_fleet(num_replicas=2):
    dep = build_gpt_deployment(
        cfg=CFG, engine_cfg=EngineConfig(max_slots=4), seed=SEED,
        num_replicas=num_replicas)
    handle = serve.run(dep, use_actors=False)
    f = fleet.enable("v1", FleetConfig(rate=500, burst=64))
    return handle, f


def _serving_replica(st, f):
    """The replica the last route event picked (stream in flight)."""
    tag = [e for e in f.events() if e["kind"] == "route"][-1]["replica"]
    with st._lock:
        return next(r for r in st.replicas if r.tag == tag)


# ------------------------------------------------ (1) drain + real kill


def test_replica_drain_interrupted_by_kill_resumes_token_exact():
    """A replica being DRAINED dies for real before it finishes its
    in-flight stream (chaos kill scripted at the replica_drain point):
    the fallback is the token-exact resume path — the client still sees
    one seamless stream, and the re-route is classified as a SCALE-DOWN
    resume (the replica had already left "active")."""
    handle, f = _run_fleet(num_replicas=2)
    st = serve.get_handle("v1")._state
    prompt, max_tokens = [9, 2, 6], 24

    def kill_mid_drain(ctx):
        # a genuine crash landing exactly when the drain begins
        ctx["state"].fleet.kill_replica(ctx["replica"])

    plan = fault_injection.FaultPlan(seed=0)
    plan.script(kill_mid_drain, point="replica_drain", nth=1)

    gen = handle.remote({"prompt": prompt, "max_tokens": max_tokens,
                         "stream": True}).result(timeout=120)
    chunks = [next(gen)]
    victim = _serving_replica(st, f)
    with fault_injection.injected(plan):
        st.drain_replicas(1, 30.0, replicas=[victim])
        for c in gen:
            chunks.append(c)
    toks = [c["token"] for c in chunks if "token" in c]
    assert toks == _ref_tokens(prompt, max_tokens)
    assert [c["index"] for c in chunks if "token" in c] \
        == list(range(max_tokens))
    snap = f.fleet_snapshot()
    assert snap["resumed_scale_down"] >= 1
    assert snap["resumed_failure"] == 0
    assert snap["admitted"] == snap["completed"] + snap["errored"] \
        + snap["cancelled"]
    assert any(p == "replica_drain" for p, _, _ in plan.log)


# --------------------------------- (2) node killed mid-decommission


def test_node_killed_mid_decommission_recovers_via_lineage():
    """The handoff is NOT load-bearing for durability: a node hard-
    killed just before its owned-object handoff ships (scripted at
    node_drain_handoff) loses the handoff entirely — and the object is
    STILL recovered, by lineage re-execution on the owner."""
    c = Cluster()
    n0 = c.add_node(num_cpus=2)
    a = c.add_node(num_cpus=2, resources={"tag": 2})
    b = c.add_node(num_cpus=2, resources={"tag": 2})
    try:
        c.wait_for_nodes()
        ray_tpu.init(address=n0.address)

        @ray_tpu.remote(resources={"tag": 1})
        def produce():
            return np.arange(200_000, dtype=np.int64)   # shm-sized

        ref = produce.remote()
        ob = ref.id.binary()
        deadline = time.time() + 60
        while time.time() < deadline:
            orec = n0.owned.get(ob)
            if orec is not None and orec.locations \
                    and ob not in n0._fwd_by_oid:
                break
            time.sleep(0.05)
        else:
            pytest.fail("producer never settled")
        holder_hex = next(iter(n0.owned[ob].locations))
        victim = next(n for n in (a, b)
                      if n.node_id.hex() == holder_hex)

        def hard_kill(ctx):
            ctx["node"]._stop.set()    # dies before the handoff ships

        plan = fault_injection.FaultPlan(seed=0)
        plan.script(hard_kill, point="node_drain_handoff", nth=1)
        with fault_injection.injected(plan):
            ray_tpu.drain_node(victim.node_id.hex(), deadline_s=10)
            out = ray_tpu.get(ref, timeout=120)
        assert out.shape == (200_000,) and out[123] == 123
        recons = sum(lin["recons"] for lin in n0.lineage.values())
        assert recons >= 1, "mid-decommission kill must fall back to " \
                            "lineage reconstruction"
        assert any(p == "node_drain_handoff" for p, _, _ in plan.log)
    finally:
        ray_tpu.shutdown()
        c.shutdown()


# ------------------------------------------- (3) deadline expiry path


def test_drain_deadline_expiry_takes_explicit_timeout_path():
    """A drain whose deadline passes with work still in flight falls
    back to kill+resume EXPLICITLY: counted as drain_timeout (never
    ``drained``, never masked), the stream resumes token-exact on a
    survivor, and the re-route is classified resumed_scale_down."""
    handle, f = _run_fleet(num_replicas=2)
    st = serve.get_handle("v1")._state
    prompt, max_tokens = [5, 5], 48

    fired = []
    plan = fault_injection.FaultPlan(seed=0)
    plan.script(lambda ctx: fired.append(ctx["replica"].tag),
                point="replica_drain_timeout", nth=1)

    gen = handle.remote({"prompt": prompt, "max_tokens": max_tokens,
                         "stream": True}).result(timeout=120)
    chunks = [next(gen)]
    victim = _serving_replica(st, f)
    with fault_injection.injected(plan):
        st.drain_replicas(1, 0.0, replicas=[victim])  # already expired
        st.drain_tick()        # deterministic: don't race the 250ms tick
        for c in gen:
            chunks.append(c)
    toks = [c["token"] for c in chunks if "token" in c]
    assert toks == _ref_tokens(prompt, max_tokens)
    snap = f.fleet_snapshot()
    assert snap["drain_timeout"] == 1
    assert snap["resumed_scale_down"] >= 1
    assert snap["resumed_failure"] == 0
    assert fired == [victim.tag]
    kinds = [e["kind"] for e in f.events()]
    assert "drain_timeout" in kinds and "drain_complete" not in kinds
