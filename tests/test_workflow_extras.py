"""Workflow extensions tests: continuations, events, virtual actors
(reference test model: python/ray/workflow/tests/)."""

import threading
import time

import pytest

from ray_tpu import workflow
from ray_tpu.dag.dag_node import FunctionNode
from ray_tpu.workflow.extras import (Continuation, HTTPEventProvider,
                                     TimerListener, VirtualActorHandle,
                                     continuation, virtual_actor,
                                     wait_for_event)


def _bind(fn, *args, **kwargs):
    return FunctionNode(fn, args, kwargs, options={})


class TestContinuation:
    def test_tail_recursion(self, tmp_path):
        def countdown(n):
            if n <= 0:
                return "done"
            return continuation(_bind(countdown, n - 1))

        out = workflow.run(_bind(countdown, 4),
                           workflow_id="wf_cont",
                           storage=str(tmp_path))
        assert out == "done"
        # every continuation level durably checkpointed
        assert workflow.get_output("wf_cont",
                                   storage=str(tmp_path)) == "done"

    def test_continuation_resume_skips(self, tmp_path):
        calls = []

        def a():
            calls.append("a")
            return continuation(_bind(b))

        def b():
            calls.append("b")
            return 42

        assert workflow.run(_bind(a), workflow_id="wf_c2",
                            storage=str(tmp_path)) == 42
        n = len(calls)
        assert workflow.resume("wf_c2", _bind(a),
                               storage=str(tmp_path)) == 42
        assert len(calls) == n  # all levels memoized


class TestEvents:
    def test_timer_listener(self):
        t0 = time.time()
        payload = TimerListener(time.time() + 0.2).poll_for_event()
        assert time.time() - t0 >= 0.15
        assert "fired_at" in payload

    def test_wait_for_event_in_workflow(self, tmp_path):
        provider = HTTPEventProvider(port=0)
        try:
            def post_later():
                time.sleep(0.3)
                import json
                import urllib.request
                req = urllib.request.Request(
                    provider.address + "/event",
                    data=json.dumps({"key": "go",
                                     "payload": {"x": 7}}).encode(),
                    headers={"Content-Type": "application/json"})
                urllib.request.urlopen(req, timeout=10).read()

            threading.Thread(target=post_later, daemon=True).start()
            node = wait_for_event(
                lambda: provider.event_key_listener("go"), timeout=30)
            out = workflow.run(node, workflow_id="wf_evt",
                               storage=str(tmp_path))
            assert out == {"x": 7}
            # resume does not re-wait: result is durable
            out2 = workflow.resume("wf_evt", node,
                                   storage=str(tmp_path))
            assert out2 == {"x": 7}
        finally:
            provider.stop()


class TestVirtualActor:
    def test_state_survives_handles(self, tmp_path):
        @virtual_actor
        class Counter:
            def __init__(self):
                self.n = 0

            def incr(self, k=1):
                self.n += k
                return self.n

            def value(self):
                return self.n

        h1 = Counter.get_or_create("c1", storage=str(tmp_path))
        assert h1.incr() == 1
        assert h1.incr(5) == 6
        # a brand-new handle (fresh process analogue) sees durable state
        h2 = Counter.get_or_create("c1", storage=str(tmp_path))
        assert h2.value() == 6
        # distinct actor id = distinct state
        h3 = Counter.get_or_create("c2", storage=str(tmp_path))
        assert h3.value() == 0
        h1.delete()
        h4 = Counter.get_or_create("c1", storage=str(tmp_path))
        assert h4.value() == 0

    def test_virtual_actor_rejects_private(self, tmp_path):
        @virtual_actor
        class A:
            def __init__(self):
                self.x = 1

        h = A.get_or_create("a1", storage=str(tmp_path))
        with pytest.raises(AttributeError):
            h._private()
