"""Attention kernels: flash (pallas, interpreted on CPU) and ring
attention vs the reference implementation."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from ray_tpu.parallel.jax_compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.ops import attention, flash_attention, mha_reference, ring_attention


def _qkv(key, b=2, h=4, s=256, d=64, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, h, s, d), dtype)
    k = jax.random.normal(kk, (b, h, s, d), dtype)
    v = jax.random.normal(kv, (b, h, s, d), dtype)
    return q, k, v


# On TPU the MXU runs f32 matmuls at bf16-ish precision by default, so two
# correct implementations with different blocking differ at ~1e-2.
TOL = dict(atol=2e-2, rtol=2e-2) if jax.default_backend() == "tpu" \
    else dict(atol=2e-4, rtol=2e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_reference(causal):
    q, k, v = _qkv(jax.random.PRNGKey(0))
    out_ref = mha_reference(q, k, v, causal=causal)
    out_flash = flash_attention(q, k, v, causal=causal,
                                block_q=128, block_k=128)
    np.testing.assert_allclose(out_ref, out_flash, **TOL)


def test_flash_grads_match_reference():
    q, k, v = _qkv(jax.random.PRNGKey(1), s=128)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True) ** 2)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       block_q=128, block_k=64) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_fl = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_fl):
        a, b = np.asarray(a), np.asarray(b)
        # a handful of elements hit the worst-case MXU rounding; bound the
        # bulk tightly and the tail loosely
        assert np.mean(np.abs(a - b)) < 1e-3
        np.testing.assert_allclose(a, b, atol=0.1, rtol=0.1)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_fused_pallas_backward(causal):
    """Block-aligned shapes route to the fused pallas dkv/dq kernels
    (block_k % 128 == 0); verify against the dense reference grads."""
    q, k, v = _qkv(jax.random.PRNGKey(7), b=2, h=2, s=512, d=64)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=causal) ** 2)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal,
                                       block_q=128, block_k=128) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_fl = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_ref, g_fl):
        a, b = np.asarray(a), np.asarray(b)
        assert np.mean(np.abs(a - b)) < 1e-3
        np.testing.assert_allclose(a, b, atol=0.1, rtol=0.1)


def test_flash_fused_backward_cross_length():
    """q shorter than kv (block-aligned): fused kernels honor the causal
    diagonal offset used by decode-style shapes."""
    key = jax.random.PRNGKey(8)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (1, 2, 128, 64))
    k = jax.random.normal(kk, (1, 2, 384, 64))
    v = jax.random.normal(kv, (1, 2, 384, 64))

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True) ** 2)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       block_q=128, block_k=128) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_fl = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_fl):
        a, b = np.asarray(a), np.asarray(b)
        assert np.mean(np.abs(a - b)) < 1e-3
        np.testing.assert_allclose(a, b, atol=0.1, rtol=0.1)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_ragged_kv_padding(causal):
    """kv_len not a multiple of block_k (200 % 128 != 0): the forward
    zero-pads kv and masks padded columns — regression for the former
    in-kernel ds-clamp scheme, which read zeros past the array bound in
    interpret mode."""
    key = jax.random.PRNGKey(9)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (1, 2, 200, 64))
    k = jax.random.normal(kk, (1, 2, 200, 64))
    v = jax.random.normal(kv, (1, 2, 200, 64))
    out = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), **TOL)

    def loss_f(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal,
                                       block_q=128, block_k=128) ** 2)

    def loss_r(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=causal) ** 2)

    g_f = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
    g_r = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_r, g_f):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-3, rtol=1e-3)


def test_attention_dispatch_runs():
    q, k, v = _qkv(jax.random.PRNGKey(2), s=128)
    out = attention(q, k, v, causal=True)
    np.testing.assert_allclose(out, mha_reference(q, k, v, causal=True),
                               **TOL)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_reference(cpu_mesh_devices, causal):
    b, h, s, d = 2, 2, 256, 32
    q, k, v = _qkv(jax.random.PRNGKey(3), b=b, h=h, s=s, d=d)
    mesh = Mesh(np.array(cpu_mesh_devices[:4]), ("sp",))
    shd = NamedSharding(mesh, P(None, None, "sp", None))

    def f(q, k, v):
        return ring_attention(q, k, v, "sp", causal=causal)

    out = jax.jit(shard_map(
        f, mesh=mesh,
        in_specs=(P(None, None, "sp", None),) * 3,
        out_specs=P(None, None, "sp", None)))(
        jax.device_put(q, shd), jax.device_put(k, shd),
        jax.device_put(v, shd))
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               **TOL)


def test_ring_attention_grad(cpu_mesh_devices):
    b, h, s, d = 1, 2, 128, 16
    q, k, v = _qkv(jax.random.PRNGKey(4), b=b, h=h, s=s, d=d)
    mesh = Mesh(np.array(cpu_mesh_devices[:4]), ("sp",))
    shd = NamedSharding(mesh, P(None, None, "sp", None))
    spec = P(None, None, "sp", None)

    def ring_loss(q, k, v):
        f = shard_map(lambda a, b_, c: ring_attention(a, b_, c, "sp"),
                      mesh=mesh, in_specs=(spec,) * 3, out_specs=spec)
        return jnp.sum(f(q, k, v) ** 2)

    def ref_loss(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True) ** 2)

    g_ring = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(
        jax.device_put(q, shd), jax.device_put(k, shd),
        jax.device_put(v, shd))
    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   **TOL)


def test_flash_cross_length_causal():
    """Decode-with-kv-cache shape: q shorter than kv, causal offset must
    match mha_reference's (k_len - q_len) convention."""
    import jax, jax.numpy as jnp
    from ray_tpu.ops.attention import mha_reference
    from ray_tpu.ops.flash_attention import flash_attention
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(k1, (1, 2, 64, 64))
    k = jax.random.normal(k2, (1, 2, 128, 64))
    v = jax.random.normal(k3, (1, 2, 128, 64))
    out = flash_attention(q, k, v, causal=True, block_q=32, block_k=32)
    ref = mha_reference(q, k, v, causal=True)
    import numpy as np
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_ragged_kv_blocks():
    """kv_len not a multiple of block_k: the clamped last block must not
    double-count keys."""
    import jax, jax.numpy as jnp, numpy as np
    from ray_tpu.ops.attention import mha_reference
    from ray_tpu.ops.flash_attention import flash_attention
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(k1, (1, 1, 96, 64))
    k = jax.random.normal(k2, (1, 1, 96, 64))
    v = jax.random.normal(k3, (1, 1, 96, 64))
    for causal in (False, True):
        out = flash_attention(q, k, v, causal=causal,
                              block_q=32, block_k=64)
        ref = mha_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)


def test_attention_mask_flash_raises():
    import jax, jax.numpy as jnp, pytest as _pytest
    from ray_tpu.ops.attention import attention
    q = jnp.zeros((1, 1, 8, 16))
    mask = jnp.ones((1, 1, 8, 8), bool)
    with _pytest.raises(ValueError):
        attention(q, q, q, mask=mask, impl="flash")
