"""Multi-node core tests: routing, cross-node objects/actors, recovery.

Reference test-strategy analogue: python/ray/tests/test_multi_node*.py +
test_object_manager.py, run against the in-process virtual cluster
(reference conftest fixture: python/ray/tests/conftest.py:375).
"""

from __future__ import annotations

import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def cluster():
    c = Cluster()
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def _my_node_id():
    from ray_tpu.core.runtime import get_runtime
    return get_runtime().client.node_id


def test_membership_and_cluster_resources(cluster):
    cluster.add_node(num_cpus=1)
    cluster.add_node(num_cpus=2)
    cluster.wait_for_nodes()
    assert len([n for n in cluster.head.nodes.values() if n.alive]) == 2
    ray_tpu.init(address=cluster.nodes[0].address)
    res = ray_tpu.cluster_resources()
    assert res["CPU"] == 3.0


def test_remote_task_routing_and_cross_node_get(cluster):
    n0 = cluster.add_node(num_cpus=1)
    n1 = cluster.add_node(num_cpus=1, resources={"tag1": 2})
    cluster.wait_for_nodes()
    ray_tpu.init(address=n0.address)

    @ray_tpu.remote(resources={"tag1": 1})
    def where():
        return _my_node_id()

    # routed to n1 (only node with tag1); small result pulled back inline
    assert ray_tpu.get(where.remote(), timeout=90) == n1.node_id.hex()

    @ray_tpu.remote(resources={"tag1": 1})
    def big():
        return np.arange(300_000, dtype=np.int64)   # 2.4MB -> shm + chunks

    out = ray_tpu.get(big.remote(), timeout=90)
    assert out.shape == (300_000,) and out[-1] == 299_999

    # cross-node ARG: a large driver put (stored on n0) consumed on n1
    ref = ray_tpu.put(np.ones(200_000, dtype=np.float64))

    @ray_tpu.remote(resources={"tag1": 1})
    def consume(a):
        return float(a.sum())

    assert ray_tpu.get(consume.remote(ref), timeout=90) == 200_000.0


def test_spillover_scheduling(cluster):
    n0 = cluster.add_node(num_cpus=1)
    n1 = cluster.add_node(num_cpus=2)
    cluster.wait_for_nodes()
    ray_tpu.init(address=n0.address)

    @ray_tpu.remote
    def busy():
        time.sleep(2.0)
        return _my_node_id()

    refs = [busy.remote() for _ in range(4)]
    nodes = set(ray_tpu.get(refs, timeout=120))
    assert n1.node_id.hex() in nodes, nodes   # load spilled over
    assert len(nodes) == 2, nodes             # and n0 still ran some


def test_actor_on_remote_node(cluster):
    n0 = cluster.add_node(num_cpus=1)
    n1 = cluster.add_node(num_cpus=1, resources={"tag1": 1})
    cluster.wait_for_nodes()
    ray_tpu.init(address=n0.address)

    @ray_tpu.remote(resources={"tag1": 1})
    class Counter:
        def __init__(self):
            self.x = 0

        def incr(self, by=1):
            self.x += by
            return self.x

        def node(self):
            return _my_node_id()

        def blob(self):
            return np.full(200_000, 7, dtype=np.int32)

    c = Counter.remote()
    assert ray_tpu.get(c.node.remote(), timeout=90) == n1.node_id.hex()
    assert ray_tpu.get([c.incr.remote(), c.incr.remote(2)],
                       timeout=60) == [1, 3]
    assert int(ray_tpu.get(c.blob.remote(), timeout=60)[0]) == 7


def test_named_actor_across_nodes(cluster):
    n0 = cluster.add_node(num_cpus=1)
    cluster.add_node(num_cpus=1, resources={"tag1": 1})
    cluster.wait_for_nodes()
    ray_tpu.init(address=n0.address)

    @ray_tpu.remote(resources={"tag1": 1})
    class KV:
        def __init__(self):
            self.d = {}

        def put(self, k, v):
            self.d[k] = v

        def get(self, k):
            return self.d.get(k)

    KV.options(name="store").remote()
    h = ray_tpu.get_actor("store")
    ray_tpu.get(h.put.remote("a", 41), timeout=90)
    assert ray_tpu.get(h.get.remote("a"), timeout=60) == 41


def test_kv_and_functions_cluster_scope(cluster):
    n0 = cluster.add_node(num_cpus=1)
    cluster.add_node(num_cpus=1, resources={"tag1": 1})
    cluster.wait_for_nodes()
    ray_tpu.init(address=n0.address)

    rt = ray_tpu.get_runtime()
    rt.client.kv_put(b"shared_key", b"shared_val")

    @ray_tpu.remote(resources={"tag1": 1})
    def read_kv():
        from ray_tpu.core.runtime import get_runtime
        return get_runtime().client.kv_get(b"shared_key")

    # the remote worker reads the same KV through ITS node's head proxy,
    # and the function pickle itself travelled n0 -> head -> n1
    assert ray_tpu.get(read_kv.remote(), timeout=90) == b"shared_val"


def test_cross_node_placement_group_spread(cluster):
    n0 = cluster.add_node(num_cpus=1)
    n1 = cluster.add_node(num_cpus=1)
    cluster.wait_for_nodes()
    ray_tpu.init(address=n0.address)

    pg = ray_tpu.placement_group([{"CPU": 1}, {"CPU": 1}],
                                 strategy="STRICT_SPREAD")

    @ray_tpu.remote
    def where():
        return _my_node_id()

    a = where.options(placement_group=pg, placement_group_bundle_index=0)
    b = where.options(placement_group=pg, placement_group_bundle_index=1)
    hosts = sorted(ray_tpu.get([a.remote(), b.remote()], timeout=120))
    assert hosts == sorted([n0.node_id.hex(), n1.node_id.hex()])
    ray_tpu.remove_placement_group(pg)


def test_forwarded_task_retries_on_node_death(cluster):
    n0 = cluster.add_node(num_cpus=1)
    n1 = cluster.add_node(num_cpus=1, resources={"tag1": 2})
    cluster.wait_for_nodes()
    ray_tpu.init(address=n0.address)

    @ray_tpu.remote(resources={"tag1": 1}, max_retries=1)
    def slow():
        time.sleep(30)
        return _my_node_id()

    @ray_tpu.remote(max_retries=1)
    def portable():
        time.sleep(1.0)
        return _my_node_id()

    doomed = slow.remote()           # pinned to n1 forever; dies with it
    ref = portable.remote()          # may run anywhere

    # wait until n1 is actually executing something, then kill it
    deadline = time.time() + 60
    while time.time() < deadline:
        if any(tr.state == "running" for tr in n1.tasks.values()):
            break
        time.sleep(0.1)
    else:
        pytest.fail("n1 never started the forwarded task")
    cluster.kill_node(n1)

    # the portable task must complete (retried wherever feasible)
    assert ray_tpu.get(ref, timeout=120) in (n0.node_id.hex(),
                                             n1.node_id.hex())
    # the pinned task becomes infeasible once n1 is gone -> clear error
    with pytest.raises(Exception):
        ray_tpu.get(doomed, timeout=120)


def test_actor_restart_on_node_death(cluster):
    n0 = cluster.add_node(num_cpus=1)
    n1 = cluster.add_node(num_cpus=1, resources={"spot": 1})
    n2 = cluster.add_node(num_cpus=1, resources={"spot": 1})
    cluster.wait_for_nodes()
    ray_tpu.init(address=n0.address)

    @ray_tpu.remote(resources={"spot": 1}, max_restarts=1)
    class Phoenix:
        def node(self):
            return _my_node_id()

    p = Phoenix.remote()
    first = ray_tpu.get(p.node.remote(), timeout=90)
    assert first in (n1.node_id.hex(), n2.node_id.hex())
    victim = n1 if first == n1.node_id.hex() else n2
    survivor = n2 if victim is n1 else n1
    cluster.kill_node(victim)

    # the head re-places the actor on the surviving tagged node
    deadline = time.time() + 90
    second = None
    while time.time() < deadline:
        try:
            second = ray_tpu.get(p.node.remote(), timeout=30)
            break
        except Exception:
            time.sleep(0.5)
    assert second == survivor.node_id.hex(), second


def test_head_restart_with_persistence(tmp_path):
    """Head FT: the control plane restarts from its durable snapshot on
    the same address; nodes rejoin, KV and named actors survive, and
    cross-node routing keeps working (reference: GCS server restart with
    persistent table storage, gcs_server.cc:58)."""
    c = Cluster(head_persistence=True)
    try:
        n0 = c.add_node(num_cpus=1)
        # tag1: 2 — the named actor holds one unit for its lifetime,
        # and the post-restart routing task needs the other
        n1 = c.add_node(num_cpus=1, resources={"tag1": 2})
        c.wait_for_nodes()
        ray_tpu.init(address=n0.address)
        rt = ray_tpu.get_runtime()
        rt.client.kv_put(b"durable", b"survives")

        @ray_tpu.remote(resources={"tag1": 1})
        class Keeper:
            def __init__(self):
                self.v = 7

            def get(self):
                return self.v

        Keeper.options(name="keeper").remote()
        h = ray_tpu.get_actor("keeper")
        assert ray_tpu.get(h.get.remote(), timeout=90) == 7

        c.restart_head()
        # nodes reconnect and re-assert actor liveness
        deadline = time.time() + 60
        while time.time() < deadline:
            alive = sum(1 for n in c.head.nodes.values() if n.alive)
            ads = [a for a in c.head.actors.values() if a.state == "alive"]
            if alive >= 2 and ads:
                break
            time.sleep(0.2)
        assert sum(1 for n in c.head.nodes.values() if n.alive) >= 2

        # durable KV survived the restart
        assert rt.client.kv_get(b"durable") == b"survives"
        # the named actor is resolvable and still serving its state
        h2 = ray_tpu.get_actor("keeper")
        assert ray_tpu.get(h2.get.remote(), timeout=90) == 7

        # cross-node routing works through the new head
        @ray_tpu.remote(resources={"tag1": 1})
        def where():
            return _my_node_id()

        assert ray_tpu.get(where.remote(), timeout=120) == n1.node_id.hex()
    finally:
        ray_tpu.shutdown()
        c.shutdown()


def test_placement_group_ready_blocks_until_node_frees(cluster):
    """ready() stays unresolved while the cluster is saturated and the
    head's pending-PG queue holds the group; killing the hog commits the
    2PC and resolves it (reference: gcs_placement_group_manager.h:222)."""
    n0 = cluster.add_node(num_cpus=1)
    cluster.add_node(num_cpus=1)
    cluster.wait_for_nodes()
    ray_tpu.init(address=n0.address)

    @ray_tpu.remote(num_cpus=1)
    class Hog:
        def ping(self):
            return "ok"

    hogs = [Hog.remote() for _ in range(2)]
    for h in hogs:
        assert ray_tpu.get(h.ping.remote(), timeout=90) == "ok"

    pg = ray_tpu.placement_group([{"CPU": 1}, {"CPU": 1}],
                                 strategy="STRICT_SPREAD")
    ref = pg.ready()
    import pytest as _pytest
    with _pytest.raises(Exception):
        ray_tpu.get(ref, timeout=2)

    for h in hogs:
        ray_tpu.kill(h)
    assert ray_tpu.get(pg.ready(), timeout=120) is True
    ray_tpu.remove_placement_group(pg)


def test_head_machine_loss_recovers_from_node_replica():
    """Losing the head MACHINE (local snapshot gone): a replacement head
    bootstraps from a surviving node's replicated snapshot — the
    capability the reference needs external Redis for
    (gcs_server.cc:58-61); here the cluster is the database."""
    c = Cluster(head_persistence=True)
    try:
        n0 = c.add_node(num_cpus=1)
        c.add_node(num_cpus=1)
        c.wait_for_nodes()
        ray_tpu.init(address=n0.address)
        rt = ray_tpu.get_runtime()
        rt.client.kv_put(b"replicated", b"still-here")

        # event-driven replication barrier: the head snapshots + fans
        # out synchronously and our node's replica precedes the reply
        # on its head channel — no fixed window to race under suite load
        reply = rt.client.request({"t": "head_flush"}, timeout=60)
        assert reply.get("replicated"), reply
        replica = os.path.join(c.nodes[0].session_dir,
                               "head_replica.state")
        assert os.path.exists(replica), "snapshot never replicated"

        c.restart_head(simulate_machine_loss=True)
        deadline = time.time() + 60
        while time.time() < deadline:
            if sum(1 for n in c.head.nodes.values() if n.alive) >= 2:
                break
            time.sleep(0.2)
        # the kv may need a beat to settle while nodes re-register:
        # retry until the deadline rather than asserting one-shot
        value = None
        while time.time() < deadline:
            try:
                value = rt.client.kv_get(b"replicated")
            except RuntimeError:
                value = None   # head channel still re-establishing
            if value == b"still-here":
                break
            time.sleep(0.2)
        assert value == b"still-here"
    finally:
        ray_tpu.shutdown()
        c.shutdown()
