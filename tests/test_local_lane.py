"""In-process loopback lane (core/local_lane.py): same-process
control-plane links skip the socket stack entirely.

Covers: transport selection (lane for in-process services, socket when
disabled or cross-process), end-to-end correctness over lanes, message
isolation on inter-service lanes, and close semantics.
"""

from __future__ import annotations

import time

import pytest

import ray_tpu
from ray_tpu.core import local_lane
from ray_tpu.core.local_lane import LaneConnection


def test_driver_client_uses_lane_and_runs_tasks():
    rt = ray_tpu.init(num_cpus=2, num_tpus=0)
    try:
        assert isinstance(rt.client.conn, LaneConnection), \
            "driver connected to its own in-process node over a socket"
        # no recv thread in lane mode: replies come off the node loop
        assert rt.client._recv_thread is None

        @ray_tpu.remote
        def add(a, b):
            return a + b

        assert ray_tpu.get(add.remote(2, 3), timeout=120) == 5
        # a burst exercises send_batch / posted-list delivery
        out = ray_tpu.get([add.remote(i, i) for i in range(50)],
                          timeout=120)
        assert out == [2 * i for i in range(50)]
    finally:
        ray_tpu.shutdown()


def test_lane_disabled_falls_back_to_socket(monkeypatch):
    monkeypatch.setenv("RAY_TPU_LOCAL_LANE", "0")
    rt = ray_tpu.init(num_cpus=1, num_tpus=0)
    try:
        assert not isinstance(rt.client.conn, LaneConnection)
        assert rt.client._recv_thread is not None

        @ray_tpu.remote
        def sq(x):
            return x * x

        assert ray_tpu.get(sq.remote(7), timeout=120) == 49
    finally:
        ray_tpu.shutdown()


def test_registry_lookup_only_hits_in_process_services():
    assert local_lane.lookup("127.0.0.1:1") is None
    rt = ray_tpu.init(num_cpus=1, num_tpus=0)
    try:
        svc = rt.node_service
        assert local_lane.lookup(svc.address) is svc
    finally:
        ray_tpu.shutdown()
    # unregistered at shutdown: a later same-address socket service
    # must not be shadowed by a dead registry entry
    deadline = time.time() + 10
    while time.time() < deadline and local_lane.lookup(svc.address):
        time.sleep(0.1)
    assert local_lane.lookup(svc.address) is None


def test_virtual_cluster_runs_over_lanes():
    from ray_tpu.cluster_utils import Cluster
    c = Cluster()
    try:
        n0 = c.add_node(num_cpus=1, resources={"a": 1})
        c.add_node(num_cpus=1, resources={"b": 1})
        c.wait_for_nodes()
        ray_tpu.init(address=n0.address)
        # node↔head channel of an in-process cluster is a lane
        assert isinstance(c.nodes[0].head_conn, LaneConnection)

        @ray_tpu.remote(resources={"b": 1})
        def far(x):
            return x + 1

        # forwarded task over head + peer lanes
        assert ray_tpu.get(far.remote(41), timeout=300) == 42
    finally:
        ray_tpu.shutdown()
        c.shutdown()


def test_inter_service_lane_isolates_messages():
    """copy=True lanes pickle-roundtrip both directions: the sender
    mutating a sent dict (or the receiver mutating a delivered one)
    must not leak across the link — sockets gave that isolation for
    free, and forwarded specs are mutated on both sides."""
    rt = ray_tpu.init(num_cpus=1, num_tpus=0)
    try:
        svc = rt.node_service
        from ray_tpu.core import protocol
        conn = protocol.connect(svc.address, remote=True)
        assert isinstance(conn, LaneConnection) and conn._copy
        # outbound isolation: the posted message is a deep copy
        msg = {"t": "x", "spec": {"ids": [1, 2]}}
        iso = conn._iso(msg)
        assert iso == msg and iso["spec"] is not msg["spec"] \
            and iso["spec"]["ids"] is not msg["spec"]["ids"]
        # end-to-end over the copy lane still works
        conn.send({"t": "kv_put", "reqid": 1, "key": b"iso",
                   "value": b"v", "namespace": "t"})
        assert conn.recv(timeout=30)["added"] is True
        conn.send({"t": "kv_get", "reqid": 2, "key": b"iso",
                   "namespace": "t"})
        assert conn.recv(timeout=30)["value"] == b"v"
        conn.close()
    finally:
        ray_tpu.shutdown()


def test_lane_close_unblocks_receiver():
    rt = ray_tpu.init(num_cpus=1, num_tpus=0)
    try:
        svc = rt.node_service
        from ray_tpu.core import protocol
        conn = protocol.connect(svc.address, remote=True)
        conn.close()
        with pytest.raises(protocol.ConnectionClosed):
            conn.recv(timeout=5)
        with pytest.raises(protocol.ConnectionClosed):
            conn.send({"t": "ping", "reqid": 1})
    finally:
        ray_tpu.shutdown()
