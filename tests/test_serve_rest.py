"""Serve REST API + config deploy + request metrics tests (reference
test model: python/ray/serve/tests/test_cli.py and
dashboard/modules/serve/tests)."""

import json
import urllib.request

import pytest

from ray_tpu import serve
from ray_tpu.serve.rest import (ServeRestServer, apply_config, describe,
                                shutdown_all)

# module-level deployment targets for import_path resolution ------------


@serve.deployment
class EchoApp:
    def __call__(self, x):
        return {"echo": x}


@serve.deployment
class Doubler:
    def __call__(self, x):
        return 2 * x


echo_bound = EchoApp.bind()


@pytest.fixture(autouse=True)
def _clean():
    yield
    shutdown_all()


def test_apply_config_and_describe():
    deployed = apply_config({"applications": [
        {"name": "echo",
         "import_path": "tests.test_serve_rest:echo_bound",
         "deployments": [{"name": "EchoApp", "num_replicas": 2}]}]})
    assert deployed == ["echo"]
    h = serve.get_handle("EchoApp")
    assert h.remote("hi").result(timeout=30) == {"echo": "hi"}
    doc = describe()
    assert doc["applications"]["echo"]["status"] == "RUNNING"
    assert doc["deployments"]["EchoApp"]["replicas"] == 2


def test_request_metrics_count():
    apply_config({"applications": [
        {"name": "dbl", "import_path": "tests.test_serve_rest:Doubler"}]})
    h = serve.get_handle("Doubler")
    for i in range(5):
        assert h.remote(i).result(timeout=30) == 2 * i
    st = serve.status()["Doubler"]
    assert st["requests"] == 5 and st["errors"] == 0
    assert st["latency_sum_s"] > 0
    snap = serve.metrics_snapshot()
    names = [m[0] for m in snap]
    assert "serve_requests_total" in names


def test_rest_server_roundtrip():
    server = ServeRestServer(port=0)
    try:
        cfg = {"applications": [
            {"name": "echo",
             "import_path": "tests.test_serve_rest:echo_bound"}]}
        req = urllib.request.Request(
            server.address + "/api/serve/applications/",
            data=json.dumps(cfg).encode(), method="PUT",
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as resp:
            assert json.loads(resp.read())["deployed"] == ["echo"]

        with urllib.request.urlopen(
                server.address + "/api/serve/applications/",
                timeout=30) as resp:
            doc = json.loads(resp.read())
        assert "echo" in doc["applications"]

        req = urllib.request.Request(
            server.address + "/api/serve/applications/", method="DELETE")
        with urllib.request.urlopen(req, timeout=30):
            pass
        assert describe()["applications"] == {}
    finally:
        server.stop()


def test_rest_put_bad_config_is_400():
    server = ServeRestServer(port=0)
    try:
        req = urllib.request.Request(
            server.address + "/api/serve/applications/",
            data=json.dumps({"applications": [
                {"import_path": "no_such_module:thing"}]}).encode(),
            method="PUT")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=30)
        assert ei.value.code == 400
    finally:
        server.stop()


def test_serve_cli_status(tmp_path):
    """Drive the CLI entry functions directly (reference: serve CLI)."""
    from ray_tpu.scripts import main
    server = ServeRestServer(port=0)
    try:
        apply_config({"applications": [
            {"name": "echo",
             "import_path": "tests.test_serve_rest:echo_bound"}]})
        assert main(["serve", "status", "--address",
                     server.address]) == 0
        cfgf = tmp_path / "cfg.json"
        cfgf.write_text(json.dumps({"applications": [
            {"name": "dbl",
             "import_path": "tests.test_serve_rest:Doubler"}]}))
        assert main(["serve", "deploy", str(cfgf), "--address",
                     server.address]) == 0
        assert "dbl" in describe()["applications"]
        assert main(["serve", "shutdown", "--address",
                     server.address]) == 0
        assert describe()["applications"] == {}
    finally:
        server.stop()
