"""Flight-recorder tests: lifecycle stage stamping across the worker
boundary, Prometheus histogram exposition well-formedness, the
disabled-path zero-overhead gate, and the merged Perfetto timeline
(lifecycle + spans + chaos events)."""

import json
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu.core import flight_recorder as fr


@pytest.fixture
def recorder():
    rec = fr.enable()
    rec.reset()
    yield rec
    fr.disable()


def _wait_records(rec, n, timeout=20.0):
    deadline = time.time() + timeout
    while len(rec.records) < n and time.time() < deadline:
        time.sleep(0.05)
    return rec.export_records()


# -- lifecycle stamping -----------------------------------------------------

def test_task_lifecycle_stages_recorded(recorder, rt_init):
    @ray_tpu.remote
    def f(x):
        return x + 1

    assert ray_tpu.get([f.remote(i) for i in range(4)],
                       timeout=120) == [1, 2, 3, 4]
    records = _wait_records(recorder, 4)
    assert len(records) >= 4
    rec = records[-1]
    stages = [s for s, _ in rec["stages"]]
    # the whole journey, client → node → worker → node, in order
    for want in ("submit", "encode", "node_recv", "enqueue", "dispatch",
                 "worker_recv", "exec_start", "exec_end", "result_store",
                 "done"):
        assert want in stages, (want, stages)
    assert stages.index("submit") < stages.index("dispatch") \
        < stages.index("exec_start") < stages.index("done")
    # wall-clock stamps are monotone non-decreasing
    ts = [t for _, t in rec["stages"]]
    assert ts == sorted(ts)

    summ = recorder.stage_summary()
    for want in ("dispatch", "exec_end", "total", "get_roundtrip"):
        assert want in summ
        assert summ[want]["n"] >= 1
        assert summ[want]["p99_us"] >= summ[want]["p50_us"] >= 0


def test_actor_lifecycle_stages_recorded(recorder, rt_init):
    @ray_tpu.remote
    class A:
        def ping(self):
            return "pong"

    a = A.remote()
    assert ray_tpu.get(a.ping.remote(), timeout=120) == "pong"
    records = _wait_records(recorder, 1)
    actor_recs = [r for r in records if r["name"].endswith("ping")]
    assert actor_recs
    stages = [s for s, _ in actor_recs[-1]["stages"]]
    for want in ("submit", "node_recv", "dispatch", "worker_recv",
                 "exec_start", "exec_end", "result_store", "done"):
        assert want in stages, (want, stages)


# -- /metrics histogram exposition ------------------------------------------

def test_metrics_histogram_exposition_well_formed(recorder, rt_init):
    from ray_tpu.core.runtime import get_runtime
    from ray_tpu.metrics import MetricsExporter, node_metrics_snapshot

    @ray_tpu.remote
    def f():
        return 1

    ray_tpu.get([f.remote() for _ in range(3)], timeout=120)
    _wait_records(recorder, 3)

    svc = get_runtime().node_service
    exporter = MetricsExporter(lambda: node_metrics_snapshot(svc), port=0)
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{exporter.port}/metrics",
            timeout=10).read().decode()
    finally:
        exporter.stop()

    name = "ray_tpu_task_stage_duration_seconds"
    assert f"# TYPE {name} histogram" in body
    # per-stage series: cumulative le buckets ending at +Inf, plus
    # matching _sum and _count
    lines = body.splitlines()
    stages = set()
    for ln in lines:
        if ln.startswith(f"{name}_bucket{{stage="):
            stages.add(ln.split('stage="', 1)[1].split('"', 1)[0])
    assert "dispatch" in stages and "total" in stages
    for stage in stages:
        prefix = f'{name}_bucket{{stage="{stage}",le="'
        series = [(ln.split('le="', 1)[1].split('"', 1)[0],
                   int(ln.rsplit(" ", 1)[1]))
                  for ln in lines if ln.startswith(prefix)]
        assert series, stage
        assert series[-1][0] == "+Inf"
        counts = [c for _, c in series]
        assert counts == sorted(counts)          # cumulative
        les = [float(le) for le, _ in series[:-1]]
        assert les == sorted(les)                # ascending bounds
        count_line = next(ln for ln in lines if ln.startswith(
            f'{name}_count{{stage="{stage}"}}'))
        assert int(count_line.rsplit(" ", 1)[1]) == counts[-1]
        assert any(ln.startswith(f'{name}_sum{{stage="{stage}"}}')
                   for ln in lines)
    # tick-loop health gauges ride along
    assert "# TYPE ray_tpu_queue_depth gauge" in body
    assert 'ray_tpu_queue_depth{queue="runnable_cpu"}' in body
    assert "# TYPE ray_tpu_event_loop_lag_seconds gauge" in body


# -- zero-overhead disabled path --------------------------------------------

def test_disabled_path_leaves_specs_clean(rt_init):
    fr.disable()

    @ray_tpu.remote
    def f():
        return 1

    assert ray_tpu.get(f.remote(), timeout=120) == 1
    from ray_tpu.core.runtime import get_runtime
    svc = get_runtime().node_service
    assert all(tr.spec.get("fr") is None for tr in svc.tasks.values())


def test_dispatch_gate_is_single_is_none_check():
    """The disabled-path contract, now enforced by the analyzer's
    hot-path-gate pass (ray_tpu/analysis/hotpath_pass.py): every
    REGISTERED flight-recorder AND fault-injection hook — the node
    dispatch path plus the chaos choke points in protocol.py /
    local_lane.py / service.py — compiles to a module-global load and
    an ``is None`` branch, with nothing else on the disabled path.
    This test replaces the one-off dis check PR 3 hand-wrote for three
    node methods; the registry is the coverage list now."""
    from ray_tpu.analysis import hotpath_pass
    from ray_tpu.analysis.hotpath_registry import HOT_GATES

    findings = hotpath_pass.run()
    assert findings == [], "\n".join(f.render() for f in findings)

    # the registry really covers what the old test covered... (the
    # dispatch path lives in the sched mixin since the round-12 node split)
    sched = HOT_GATES["ray_tpu.core.node_sched"]["functions"]
    for fn in ("NodeSchedMixin._dispatch_task",
               "NodeSchedMixin._make_runnable",
               "NodeSchedMixin._admit_task"):
        assert sched[fn] == "gate", fn
    # ...and the fault-injection choke points the old test missed
    assert HOT_GATES["ray_tpu.core.protocol"]["functions"][
        "Connection.send"] == "gate"
    assert HOT_GATES["ray_tpu.core.local_lane"]["functions"][
        "LaneConnection._deliver"] == "gate"
    assert HOT_GATES["ray_tpu.core.service"]["functions"][
        "EventLoopService._dispatch"] == "gate"


def test_duplicate_task_done_counts_once(recorder):
    """A chaos-duplicated task_done must not fold the same lifecycle
    twice (the consume marker survives the dup's fr re-merge)."""
    from ray_tpu.core.node import NodeService, TaskRec

    t0 = time.monotonic()
    spec = {"task_id": b"\x01" * 22, "name": "dup",
            "fr_w0": time.time(),
            "fr": [("submit", t0), ("dispatch", t0 + 0.001)]}
    tr = TaskRec(spec=spec)
    m = {"t": "task_done", "task_id": spec["task_id"],
         "fr": list(spec["fr"]) + [("result_store", t0 + 0.002)]}
    NodeService._fr_finish(object.__new__(NodeService), tr, m)
    assert len(recorder.records) == 1
    NodeService._fr_finish(object.__new__(NodeService), tr, m)   # the dup
    assert len(recorder.records) == 1
    assert recorder.stage_summary()["dispatch"]["n"] == 1


# -- merged timeline (lifecycle + spans + chaos) ----------------------------

def test_timeline_merges_lifecycle_spans_and_chaos(tmp_path):
    from ray_tpu.core import fault_injection as fi
    from ray_tpu.util import tracing

    rec = fr.enable()
    rec.reset()
    tracing.enable_tracing(str(tmp_path / "traces"))
    plan = fi.FaultPlan(seed=7)
    plan.delay_messages(0.01, msg_type="submit_task", times=2)
    ray_tpu.init(num_cpus=2, num_tpus=0)
    try:
        with fi.injected(plan):
            @ray_tpu.remote
            def f(i):
                return i

            assert ray_tpu.get([f.remote(i) for i in range(6)],
                               timeout=120) == list(range(6))
        _wait_records(rec, 6)
        assert plan.log   # the chaos rules really fired
        assert rec.export_faults()

        from ray_tpu.core.observer import observer_query
        from ray_tpu.core.runtime import get_runtime
        svc = get_runtime().node_service
        (reply,) = observer_query(svc.address, [{"t": "flight_recorder"}])
        assert reply["enabled"] and reply["records"]
        assert reply["stages"].get("dispatch", {}).get("n", 0) >= 1

        events = get_runtime().client.request(
            {"t": "state", "what": "task_events"})["data"]
        spans = tracing.collect_spans()
        from ray_tpu.util.timeline import build_trace
        trace = build_trace(task_events=events,
                            records=reply["records"],
                            spans=spans, faults=reply["faults"])
        json.dumps(trace)   # Perfetto-loadable = valid JSON
        assert trace["traceEvents"]
        cats = {e["cat"] for e in trace["traceEvents"]}
        assert {"lifecycle", "span", "chaos"} <= cats
        chaos = [e for e in trace["traceEvents"] if e["cat"] == "chaos"]
        assert all(e["ph"] == "i" for e in chaos)
        slices = [e for e in trace["traceEvents"] if e["ph"] == "X"]
        assert all(e["dur"] >= 0 for e in slices)
        # events come out time-ordered
        ts = [e["ts"] for e in trace["traceEvents"]]
        assert ts == sorted(ts)
    finally:
        ray_tpu.shutdown()
        tracing.disable_tracing()
        fr.disable()


def test_observer_reports_disabled_recorder(rt_init):
    fr.disable()
    from ray_tpu.core.observer import observer_query
    from ray_tpu.core.runtime import get_runtime
    svc = get_runtime().node_service
    (reply,) = observer_query(svc.address, [{"t": "flight_recorder"}])
    assert reply["enabled"] is False
    assert reply["records"] == [] and reply["faults"] == []
