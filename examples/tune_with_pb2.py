"""Hyperparameter search with the PB2 population-based bandit.

    python examples/tune_with_pb2.py
"""
import os as _os
import sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

from ray_tpu import tune
from ray_tpu.train.config import RunConfig
from ray_tpu.tune import PB2, TuneConfig, Tuner


class Quadratic(tune.Trainable):
    def setup(self, config):
        self.lr = config["lr"]
        self.score = 0.0

    def step(self):
        self.score += 1.0 - (self.lr - 0.7) ** 2
        return {"score": self.score, "done": self._iteration >= 9}

    def save_checkpoint(self):
        return {"score": self.score}

    def load_checkpoint(self, ck):
        self.score = ck["score"]

    def reset_config(self, cfg):
        self.lr = cfg["lr"]
        return True


if __name__ == "__main__":
    sched = PB2(metric="score", mode="max", perturbation_interval=2,
                hyperparam_bounds={"lr": (0.0, 1.0)}, seed=0)
    grid = Tuner(
        Quadratic,
        param_space={"lr": tune.grid_search([0.1, 0.5, 0.9])},
        tune_config=TuneConfig(metric="score", mode="max",
                               scheduler=sched),
        run_config=RunConfig(name="pb2_demo",
                             storage_path="/tmp/rt_pb2")).fit()
    for t in grid.trials:
        print(t.trial_id, "lr=%.3f" % t.config["lr"],
              "score=%.2f" % t.last_result["score"])
