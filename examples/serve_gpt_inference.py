"""Serve a GPT with the continuous-batching inference engine and stream
a generation over HTTP.

Run:  JAX_PLATFORMS=cpu python examples/serve_gpt_inference.py
(see ARCHITECTURE.md "Inference engine" for the slot lifecycle)."""

import json
import os
import socket
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax.numpy as jnp

from ray_tpu import serve
from ray_tpu.inference import (EngineConfig, build_gpt_deployment,
                               parse_stream_chunks)
from ray_tpu.models import gpt


def main():
    cfg = gpt.GPTConfig.tiny(dtype=jnp.float32)   # swap for gpt2_124m()
    serve.run(build_gpt_deployment(
        cfg=cfg, engine_cfg=EngineConfig(max_slots=8), seed=0),
        use_actors=False, http=True)
    addr = serve.proxy_address()
    print(f"serving at {addr}/v1/generate")

    # one-shot JSON
    import urllib.request
    req = urllib.request.Request(
        addr + "/v1/generate",
        data=json.dumps({"prompt": [3, 1, 4, 1, 5],
                         "max_tokens": 8}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=120) as resp:
        print("json:", json.loads(resp.read())["result"]["tokens"])

    # chunked token streaming (raw socket: urllib buffers whole bodies)
    host, port = addr[len("http://"):].split(":")
    body = json.dumps({"prompt": "hello", "max_tokens": 16,
                       "stream": True}).encode()
    with socket.create_connection((host, int(port)), timeout=120) as s:
        s.sendall(b"POST /v1/generate HTTP/1.1\r\nHost: x\r\n"
                  b"Content-Type: application/json\r\n"
                  + f"Content-Length: {len(body)}\r\n\r\n".encode() + body)
        buf = b""
        while b"0\r\n\r\n" not in buf:
            data = s.recv(4096)
            if not data:   # truncated stream (server signals errors by
                break      # closing without the terminal 0-chunk)
            buf += data
    payload = buf.split(b"\r\n\r\n", 1)[1]
    for chunk in parse_stream_chunks(payload):
        print("chunk:", chunk)

    serve.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
