"""Serve a jax model over HTTP + gRPC with autoscaling replicas.

    python examples/serve_model.py
"""
import os as _os
import sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import numpy as np

from ray_tpu import serve


@serve.deployment(num_replicas=2, max_concurrent_queries=8)
class Classifier:
    def __init__(self):
        import jax
        import jax.numpy as jnp
        k = jax.random.PRNGKey(0)
        self.w = jax.random.normal(k, (4, 3))
        self._predict = jax.jit(
            lambda w, x: jnp.argmax(x @ w, axis=-1))

    def __call__(self, features):
        import jax.numpy as jnp
        x = jnp.asarray(features, jnp.float32).reshape(-1, 4)
        return {"classes": np.asarray(self._predict(self.w, x)).tolist()}


if __name__ == "__main__":
    handle = serve.run(Classifier.bind(), http=True, port=8000)
    print("HTTP ingress:", serve.proxy_address())
    out = handle.remote([[0.1, 0.2, 0.3, 0.4]]).result(timeout=30)
    print("direct handle call:", out)

    from ray_tpu.serve.grpc_ingress import GrpcIngress, GrpcServeClient
    ing = GrpcIngress(serve._get_controller(), port=0)
    cli = GrpcServeClient(ing.address)
    print("gRPC call:", cli.predict("Classifier",
                                    [[1.0, 0.0, 0.0, 0.0]]))
    cli.close(); ing.stop(); serve.shutdown()
