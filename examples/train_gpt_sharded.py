"""Train GPT-2 on a dp/tp/sp device mesh with ray_tpu.train.JaxTrainer.

Run on a TPU host (uses all local chips), or on CPU for a smoke test:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/train_gpt_sharded.py
"""
import os as _os
import sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(
    _os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import optax

from ray_tpu.models import gpt
from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

CFG = gpt.GPTConfig(vocab_size=512, max_seq=128, d_model=128,
                    n_heads=4, n_layers=2, d_ff=512, remat=True)


def batches(steps: int = 10, batch: int = 8):
    key = jax.random.PRNGKey(1)
    for _ in range(steps):
        key, sub = jax.random.split(key)
        yield {"tokens": jax.random.randint(sub, (batch, CFG.max_seq + 1),
                                            0, CFG.vocab_size, jnp.int32)}


if __name__ == "__main__":
    on_cpu = jax.devices()[0].platform != "tpu"
    trainer = JaxTrainer(
        loss_fn=lambda p, b, mesh=None, rules=None: gpt.loss_fn(
            p, b, CFG, mesh=mesh, rules=rules),
        init_params=lambda rng: gpt.init_params(CFG, rng),
        optimizer=optax.adamw(3e-4),
        train_data=batches(),
        num_steps=10,
        params_logical=gpt.param_logical_axes(CFG),
        report_every=2,
        scaling_config=ScalingConfig(
            mesh={"dp": 2, "tp": 2, "sp": 2} if on_cpu else {"dp": -1},
            use_cpu_devices=on_cpu),
        run_config=RunConfig(storage_path="/tmp/rt_gpt_example"))
    result = trainer.fit()
    print("final metrics:", result.metrics)
