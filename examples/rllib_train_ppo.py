"""Train PPO on CartPole with actor rollout workers.

    python examples/rllib_train_ppo.py
"""
import os as _os
import sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import ray_tpu
from ray_tpu.rllib import PPOConfig

if __name__ == "__main__":
    ray_tpu.init(num_cpus=4, num_tpus=0)
    algo = (PPOConfig(env="CartPole-v1")
            .rollouts(num_rollout_workers=2, num_envs_per_worker=4,
                      rollout_length=64)
            .training(train_batch_size=2048, lr=3e-4)
            .build())
    for i in range(10):
        result = algo.train()
        print(f"iter {i}: reward_mean="
              f"{result.get('episode_reward_mean', 0):.1f}")
    algo.cleanup()
    ray_tpu.shutdown()
