"""Headline benchmark: GPT-2 124M training throughput (tokens/sec).

North-star config #2 (BASELINE.json): GPT-2 124M data-parallel training.
Baseline = 180k tokens/s, a published-class A100 bf16 number for GPT-2
124M with flash attention (nanoGPT-era single-A100 throughput); the
north-star target is ≥90% of the A100 equivalent (BASELINE.md), so
vs_baseline ≥ 0.9 meets target on a v5e-class chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import sys
import time


def main():
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models import gpt
    from ray_tpu.train.step import make_train_step

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"

    if on_tpu:
        cfg = gpt.GPTConfig.gpt2_124m(remat=True)
        batch, seq, steps, warmup = 16, 1024, 20, 3
    else:  # CPU smoke mode so the bench always produces a line
        cfg = gpt.GPTConfig(vocab_size=2048, max_seq=256, d_model=256,
                            n_heads=8, n_layers=4, d_ff=1024, remat=False,
                            dtype=jnp.float32)
        batch, seq, steps, warmup = 8, 256, 5, 1

    params = gpt.init_params(cfg, jax.random.PRNGKey(0))

    def loss(p, b):
        return gpt.loss_fn(p, b, cfg)

    tx = optax.adamw(3e-4, weight_decay=0.1)
    init_fn, step_fn = make_train_step(loss, tx, mesh=None)
    state = init_fn(params)

    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (batch, seq + 1), 0, cfg.vocab_size,
        dtype=jnp.int32)
    b = {"tokens": tokens}

    for _ in range(warmup):
        state, metrics = step_fn(state, b)
    jax.block_until_ready(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step_fn(state, b)
    jax.block_until_ready(metrics["loss"])
    dt = time.perf_counter() - t0

    toks_per_sec = batch * seq * steps / dt
    baseline = 180_000.0  # A100-class GPT-2 124M tokens/s (see docstring)
    out = {
        "metric": "gpt2_124m_train_throughput" if on_tpu
                  else "gpt2_cpu_smoke_train_throughput",
        "value": round(toks_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(toks_per_sec / baseline, 4),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
