"""Headline benchmark: GPT-2 124M training throughput (tokens/sec) + MFU.

North-star config #2 (BASELINE.json): GPT-2 124M data-parallel training.
Baseline = 180k tokens/s, a published-class A100 bf16 number for GPT-2
124M with flash attention (nanoGPT-era single-A100 throughput); the
north-star target is >=90% of the A100 equivalent (BASELINE.md), so
vs_baseline >= 0.9 meets target on a v5e-class chip.

Honest-timing design (round 2): execution is forced by fetching the
CONCRETE loss value to host each timed step — a host fetch of real bytes
cannot be deferred by any backend, unlike block_until_ready which some
experimental platforms treat as a no-op. MFU is computed from the actual
parameter count and a per-device-kind peak-FLOPs table; if MFU lands
outside (0, 1] or vs_baseline is implausible (>2 on one chip), the bench
reports status "implausible" instead of publishing the number.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

from __future__ import annotations

import json
import time

# bf16 peak FLOP/s per chip, by substring of jax Device.device_kind.
_PEAK_FLOPS = [
    ("v5 lite", 197e12),   # TPU v5e
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v4", 275e12),
    ("v6", 918e12),        # Trillium
    ("v3", 123e12),
    ("v2", 46e12),
    ("A100", 312e12),
    ("H100", 989e12),
]


def _peak_for(device_kind: str):
    for key, peak in _PEAK_FLOPS:
        if key.lower() in device_kind.lower():
            return peak
    return None


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ray_tpu.models import gpt
    from ray_tpu.train.step import make_train_step

    dev = jax.devices()[0]
    platform, kind = dev.platform, dev.device_kind
    on_tpu = platform == "tpu"

    if on_tpu:
        # dots remat policy: keep matmul outputs, recompute only cheap
        # elementwise work in backward (measured +3% over full remat;
        # remat=False and batch>32 exceed this environment's remote
        # compile helper limits)
        cfg = gpt.GPTConfig.gpt2_124m(remat=True, remat_policy="dots")
        batch, seq, steps, warmup = 16, 1024, 20, 3
    else:  # CPU smoke mode so the bench always produces a line
        cfg = gpt.GPTConfig(vocab_size=2048, max_seq=256, d_model=256,
                            n_heads=8, n_layers=4, d_ff=1024, remat=False,
                            dtype=jnp.float32)
        batch, seq, steps, warmup = 8, 256, 5, 1

    params = gpt.init_params(cfg, jax.random.PRNGKey(0))
    n_params = int(sum(np.prod(p.shape) for p in jax.tree_util.tree_leaves(params)))

    def loss(p, b):
        return gpt.loss_fn(p, b, cfg)

    tx = optax.adamw(3e-4, weight_decay=0.1)
    init_fn, step_fn = make_train_step(loss, tx, mesh=None)
    state = init_fn(params)

    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (batch, seq + 1), 0, cfg.vocab_size,
        dtype=jnp.int32)
    b = {"tokens": tokens}

    def run(n, per_step_sync):
        """Run n steps; returns (dt_seconds, last_loss). Forces real
        execution with concrete host fetches, not block_until_ready."""
        nonlocal state
        t0 = time.perf_counter()
        last = None
        for _ in range(n):
            state, metrics = step_fn(state, b)
            if per_step_sync:
                last = float(np.asarray(metrics["loss"]))
        if not per_step_sync:
            # final fetch forces the whole dependency chain of n steps
            last = float(np.asarray(metrics["loss"]))
        return time.perf_counter() - t0, last

    run(warmup, per_step_sync=True)  # warmup: compile + settle

    # training flops/token: 6N matmul + attention quadratic term (fwd+bwd)
    flops_per_token = 6 * n_params + 12 * cfg.n_layers * cfg.d_model * seq
    peak = _peak_for(kind)
    baseline = 180_000.0  # A100-class GPT-2 124M tokens/s (see docstring)

    def metrics_for(dt):
        tps = batch * seq * steps / dt
        mfu = (flops_per_token * tps / peak) if peak else None
        return tps, mfu

    # pass 1: end-only sync (max dispatch overlap, best-case throughput)
    dt, final_loss = run(steps, per_step_sync=False)
    toks_per_sec, mfu = metrics_for(dt)
    timing_mode = "chain_sync"

    def implausible(tps, mfu):
        if mfu is not None:
            return mfu > 1.0  # chip-normalized: >100% of peak is impossible
        # unknown chip: fall back to a raw multiple of the A100 baseline
        return on_tpu and tps / baseline > 2.0

    if implausible(toks_per_sec, mfu):
        # pass 2: strict per-step host fetch — cannot be deferred
        dt, final_loss = run(steps, per_step_sync=True)
        toks_per_sec, mfu = metrics_for(dt)
        timing_mode = "per_step_sync"

    status = "ok"
    if implausible(toks_per_sec, mfu):
        # even strict timing looks impossible: platform timing is broken;
        # refuse to publish the number as a throughput claim
        status = "implausible"

    ok = status == "ok"
    out = {
        "metric": "gpt2_124m_train_throughput" if on_tpu
                  else "gpt2_cpu_smoke_train_throughput",
        # refuse to publish an impossible number as a throughput claim
        "value": round(toks_per_sec, 1) if ok else 0.0,
        "unit": "tokens/s",
        "vs_baseline": round(toks_per_sec / baseline, 4) if ok else 0.0,
        "status": status,
        "mfu": round(mfu, 4) if (mfu is not None and ok) else None,
        "platform": platform,
        "device_kind": kind,
        "n_devices": len(jax.devices()),
        "n_params": n_params,
        "timing": timing_mode,
        "final_loss": round(final_loss, 4),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
